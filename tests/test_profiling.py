"""Device-timeline profiling plane tests (ISSUE 15; docs/observability.md
"Device timeline").

Covers the ``IGG_PROFILE`` window grammar, the blessed op-name
classification vocabulary (`utils.hlo_analysis.classify_op_name`), the
attribution parser golden-pinned on a committed fixture trace
(``tests/data/profile_fixture.trace.json.gz``: scope table AND measured
overlap fraction), the malformed-trace structured-finding contract, the
``scripts/igg_prof.py`` CLI, the cross-run diff, and — in ONE real
XLA:CPU capture shared by a module fixture — the end-to-end windowed
capture through `guarded_time_loop` (meta file, ``profile.start/stop``
events, gauges) plus ``igg_trace.py merge --device`` producing one valid
Chrome trace with host AND device tracks.  The 2-process gloo leg lives
in ``test_distributed.py::test_two_process_device_merged_trace``.
"""

import gzip
import json
import os
import sys

import pytest

import jax

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils import hlo_analysis
from implicitglobalgrid_tpu.utils import profiling
from implicitglobalgrid_tpu.utils import telemetry as tele
from implicitglobalgrid_tpu.utils import tracing

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)
FIXTURE = os.path.join(_here, "data", "profile_fixture.trace.json.gz")

sys.path.insert(0, os.path.join(_repo, "scripts"))
import igg_prof  # noqa: E402  (scripts/ CLI under test)
import igg_trace  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_state():
    profiling.reset()
    yield
    profiling.reset()


# -- window grammar -----------------------------------------------------------


def test_parse_profile_window():
    assert profiling.parse_profile_window("steps:20-40") == (20, 40)
    assert profiling.parse_profile_window("steps:5") == (1, 5)
    assert profiling.parse_profile_window("steps:3-3") == (3, 3)


@pytest.mark.parametrize(
    "bad", ["", "steps", "steps:", "steps:0-4", "steps:5-2", "steps:a-b",
            "window:2-3", "steps:2-3-4"]
)
def test_parse_profile_window_rejects(bad):
    with pytest.raises(ValueError, match="IGG_PROFILE"):
        profiling.parse_profile_window(bad)


def test_maybe_arm_invalid_spec_raises(monkeypatch):
    monkeypatch.setenv("IGG_PROFILE", "steps:banana")
    with pytest.raises(ValueError, match="IGG_PROFILE"):
        profiling.maybe_arm(0)


def test_maybe_arm_disabled_paths(monkeypatch):
    monkeypatch.delenv("IGG_PROFILE", raising=False)
    assert profiling.maybe_arm(0) is None
    monkeypatch.setenv("IGG_PROFILE", "steps:2-3")
    monkeypatch.setenv("IGG_TELEMETRY", "0")
    assert profiling.maybe_arm(0) is None


# -- op-name vocabulary -------------------------------------------------------


def test_classify_op_name_vocabulary():
    cls = hlo_analysis.classify_op_name
    assert cls("collective-permute.14") == "collective"
    assert cls("collective-permute-start.3") == "collective"
    assert cls("all-reduce.1") == "collective"
    assert cls("pad_add_fusion") == "kernel"
    assert cls("select_dynamic-update-slice_fusion.1") == "kernel"
    assert cls("custom-call.7") == "kernel"
    assert cls("copy.17") == "glue"
    assert cls("slice.96") == "glue"
    assert cls("while.19") == "glue"
    assert cls("partition-id.7") == "glue"
    # a fused collective still occupies the fabric: collective wins
    assert cls("fusion_collective-permute.2") == "collective"


# -- fixture attribution (golden) ---------------------------------------------


def test_fixture_attribution_golden():
    rec = profiling.attribute_trace(FIXTURE)
    assert rec["n_device_ops"] == 7
    assert rec["device_seconds"] == pytest.approx(0.00117)
    assert rec["scope_seconds"] == pytest.approx(
        {
            "glue": 9e-05,
            "igg_halo_exchange": 1e-04,
            "igg_interior_pass": 5e-04,
            "igg_ring_pass": 1e-04,
            "igg_slab_exchange_begin": 3e-04,
            "kernels": 8e-05,
        }
    )
    assert rec["unattributed_seconds"] == pytest.approx(9e-05)
    ov = rec["overlap"]
    # comm = slab-begin [200,500] + halo [1000,1100]; kernels = ring
    # [0,100] + interior [150,650] + custom-call [820,900]; only the
    # slab-begin hop hides under the interior -> 300/400.
    assert ov["comm_seconds"] == pytest.approx(4e-04)
    assert ov["compute_seconds"] == pytest.approx(6.8e-04)
    assert ov["overlapped_seconds"] == pytest.approx(3e-04)
    assert ov["fraction"] == pytest.approx(0.75)


def test_fixture_attribution_table_golden():
    rec = profiling.attribute_trace(FIXTURE)
    table = profiling.render_attribution_table(rec)
    assert table == (
        "scope                           device_ms   share\n"
        "-------------------------------------------------\n"
        "glue                                0.090   7.7%\n"
        "igg_halo_exchange                   0.100   8.5%\n"
        "igg_interior_pass                   0.500  42.7%\n"
        "igg_ring_pass                       0.100   8.5%\n"
        "igg_slab_exchange_begin             0.300  25.6%\n"
        "kernels                             0.080   6.8%\n"
        "-------------------------------------------------\n"
        "total                               1.170         (7 device op(s))\n"
        "overlap: comm 0.400 ms, compute 0.680 ms, overlapped 0.300 ms "
        "-> fraction 0.7500"
    )


def test_attribution_zero_collectives_has_no_fake_fraction():
    # a capture without collectives must answer None, never 0.0
    doc = {
        "traceEvents": [
            {"ph": "X", "pid": 0, "tid": 0, "name": "fusion.1", "ts": 0.0,
             "dur": 10.0, "args": {"hlo_op": "fusion.1"}},
        ]
    }
    rec = profiling.attribute_trace(doc)
    assert rec["overlap"]["fraction"] is None
    assert rec["scope_seconds"] == {"kernels": 1e-05}


def test_host_only_trace_is_an_answer_not_an_error():
    doc = {"traceEvents": [
        {"ph": "X", "pid": 0, "tid": 0, "name": "python", "ts": 0.0,
         "dur": 5.0},
    ]}
    rec = profiling.attribute_trace(doc)
    assert rec["n_device_ops"] == 0
    assert rec["overlap"]["fraction"] is None


# -- malformed input: structured finding, not a traceback ---------------------


def test_malformed_trace_raises_valueerror(tmp_path):
    bad = tmp_path / "broken.trace.json.gz"
    bad.write_bytes(gzip.compress(b"{not json"))
    with pytest.raises(ValueError, match="malformed trace JSON"):
        profiling.load_trace(str(bad))
    truncated = tmp_path / "torn.trace.json.gz"
    whole = gzip.compress(b'{"traceEvents": []}')
    truncated.write_bytes(whole[: len(whole) // 2])
    with pytest.raises(ValueError):
        profiling.load_trace(str(truncated))
    notatrace = tmp_path / "other.trace.json.gz"
    notatrace.write_bytes(gzip.compress(b'{"foo": 1}'))
    with pytest.raises(ValueError, match="no traceEvents"):
        profiling.load_trace(str(notatrace))


def test_igg_prof_cli_malformed_trace_is_structured_finding(tmp_path, capsys):
    bad = tmp_path / "broken.trace.json.gz"
    bad.write_bytes(gzip.compress(b"{not json"))
    rc = igg_prof.main(["attribute", str(bad)])
    out = capsys.readouterr().out.strip()
    finding = json.loads(out)  # one parseable JSON finding, no traceback
    assert rc == 1
    assert finding["finding"] == "profile.parse_failed"
    assert "malformed" in finding["error"]


def test_igg_prof_cli_attribute_and_diff(capsys):
    assert igg_prof.main(["attribute", FIXTURE, "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["overlap"]["fraction"] == pytest.approx(0.75)
    assert igg_prof.main(["diff", FIXTURE, FIXTURE]) == 0
    table = capsys.readouterr().out
    assert "overlap fraction: A 0.7500 -> B 0.7500" in table
    assert "worst regression" not in table  # identical runs drift nowhere


def test_attribution_delta_names_the_scope_that_ate_it():
    a = {"scope_seconds": {"igg_interior_pass": 0.5, "glue": 0.1},
         "device_seconds": 0.6, "overlap": {"fraction": 0.8}}
    b = {"scope_seconds": {"igg_interior_pass": 0.5, "glue": 0.4},
         "device_seconds": 0.9, "overlap": {"fraction": 0.5}}
    delta = profiling.attribution_delta(a, b)
    assert delta["worst"] == "glue"
    assert delta["worst_delta_s"] == pytest.approx(0.3)
    assert delta["scopes"]["igg_interior_pass"]["delta_s"] == 0.0
    assert delta["overlap_fraction"] == {"a": 0.8, "b": 0.5}
    txt = profiling.render_delta_table(delta)
    assert "worst regression: glue" in txt


# -- capture degradations -----------------------------------------------------


def test_capture_without_directory_degrades_to_structured_failure(monkeypatch):
    monkeypatch.delenv("IGG_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("IGG_PROFILE_DIR", raising=False)
    monkeypatch.setenv("IGG_PROFILE", "steps:1-2")
    tele.reset()
    cap = profiling.maybe_arm(0)
    assert cap is not None and cap.done  # failed at start, disarmed
    snap = tele.snapshot()
    assert snap["counters"].get("profile.capture_failures") == 1
    # the pipeline keeps running: further steps are no-ops, not errors
    cap.on_step(1)
    cap.on_step(2)
    cap.close("test")


def test_window_past_run_end_never_starts(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("IGG_PROFILE", "steps:50-60")
    cap = profiling.maybe_arm(0)
    for it in range(1, 5):
        cap.on_step(it)
    cap.close("run_complete")
    assert not cap.started
    assert profiling.find_capture_metas(str(tmp_path)) == []


# -- the real XLA:CPU capture (one profiler session, shared) ------------------


@pytest.fixture(scope="module")
def captured_run(tmp_path_factory):
    """ONE windowed end-to-end capture through `guarded_time_loop` on the
    8-device mesh (profiler sessions cost seconds — every end-to-end
    assertion below reads this run's artifacts)."""
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils.resilience import (
        RunGuard,
        guarded_time_loop,
    )
    from implicitglobalgrid_tpu.utils.telemetry import teff_bytes

    tdir = str(tmp_path_factory.mktemp("profile_run"))
    saved = {
        k: os.environ.get(k)
        for k in ("IGG_TELEMETRY_DIR", "IGG_PROFILE", "IGG_PROFILE_DIR")
    }
    os.environ["IGG_TELEMETRY_DIR"] = tdir
    os.environ["IGG_PROFILE"] = "steps:2-3"
    os.environ.pop("IGG_PROFILE_DIR", None)
    tele.reset()
    tracing.reset()
    profiling.reset()
    try:
        igg.init_global_grid(8, 8, 8, quiet=True)
        state, params = diffusion3d.setup(8, 8, 8, init_grid=False)
        guarded_time_loop(
            diffusion3d.make_step(params, donate=False), state, 4,
            guard=RunGuard(), sync_every_step=True, model="diffusion3d",
            bytes_per_step=teff_bytes(state[:1]),
        )
        trace_path = igg.dump_trace(tdir)
        snap = tele.snapshot()
        events = tele.read_events(os.path.join(tdir, "events.jsonl"))
    finally:
        igg.finalize_global_grid()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {
        "tdir": tdir,
        "host_trace": trace_path,
        "snapshot": snap,
        "events": events,
    }


def test_windowed_capture_end_to_end(captured_run):
    metas = profiling.find_capture_metas(captured_run["tdir"])
    assert len(metas) == 1 and metas[0].endswith("profile.p0.json")
    meta = json.load(open(metas[0]))
    assert meta["schema"] == profiling.PROFILE_SCHEMA
    assert meta["window"] == [2, 3]
    assert meta["started_at_step"] == 2 and meta["stopped_at_step"] == 3
    assert os.path.isfile(meta["trace_path"])
    assert meta["trace_path"].endswith(".trace.json.gz")
    assert meta["t_stop_perf"] > meta["t_start_perf"]
    attribution = meta["attribution"]
    assert "error" not in attribution
    assert attribution["n_device_ops"] > 0
    # the 8-device mesh's step has real collective-permutes: both comm and
    # kernel time exist, so the overlap fraction is a measured number
    assert attribution["scope_seconds"].get("collectives", 0) > 0
    assert attribution["scope_seconds"].get("kernels", 0) > 0
    assert attribution["overlap"]["fraction"] is not None
    assert 0.0 <= attribution["overlap"]["fraction"] <= 1.0


def test_capture_events_and_gauges(captured_run):
    types = [e["type"] for e in captured_run["events"]]
    assert "profile.start" in types and "profile.stop" in types
    start = next(
        e for e in captured_run["events"] if e["type"] == "profile.start"
    )
    stop = next(
        e for e in captured_run["events"] if e["type"] == "profile.stop"
    )
    assert start["window"] == [2, 3] and start["step"] == 2
    assert stop["step"] == 3 and stop["reason"] == "window"
    assert stop["trace"].endswith(".trace.json.gz")
    gauges = captured_run["snapshot"]["gauges"]
    assert gauges.get("profile.scope_seconds.collectives", 0) > 0
    assert "profile.overlap_fraction" in gauges
    assert captured_run["snapshot"]["counters"].get("profile.captures") == 1


def test_merge_device_produces_one_valid_trace(captured_run, tmp_path):
    """Acceptance: windowed capture -> parse -> attribution ->
    ``igg_trace.py merge --device`` = ONE valid Chrome trace with host +
    device tracks on the same rank pid."""
    out = str(tmp_path / "merged.json")
    rc = igg_trace.main(
        ["merge", captured_run["tdir"], "--device", "-o", out]
    )
    assert rc == 0
    doc = json.load(open(out))
    assert tracing.validate_chrome_trace(doc) == []
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    host = [e for e in xs if not (e.get("args") or {}).get("hlo_op")]
    device = [e for e in xs if (e.get("args") or {}).get("hlo_op")]
    assert host and device
    assert {e["pid"] for e in device} == {0}  # the rank's own track
    assert all(e["tid"] >= profiling.DEVICE_TID_BASE for e in device)
    assert "igg.step" in {e["name"] for e in host}
    # every device event carries its attribution bucket for the viewer
    assert all((e["args"].get("igg_scope") or "") for e in device)
    align = doc["otherData"]["device_alignment"]
    assert "per_rank" in align and align["per_rank"]["0"]["n_ops"] > 0
    assert "start latency" in align["note"]  # the honesty bound, recorded


def test_merge_device_with_explicit_trace_files(captured_run, tmp_path):
    """--device must also work in the explicit-file form the stale-refusal
    remedy prescribes ('merge the current run's files explicitly'): metas
    are discovered next to the named trace files."""
    out = str(tmp_path / "merged_explicit.json")
    trace_file = os.path.join(captured_run["tdir"], "trace.p0.json")
    assert igg_trace.main(["merge", trace_file, "--device", "-o", out]) == 0
    doc = json.load(open(out))
    assert tracing.validate_chrome_trace(doc) == []
    assert any(
        (e.get("args") or {}).get("hlo_op")
        for e in doc["traceEvents"]
        if e.get("ph") == "X"
    )


def test_merge_device_without_metas_is_a_clear_error(tmp_path, capsys):
    # host trace but no capture meta: merge --device must say what to do
    tracing.reset()
    with tracing.trace_span("igg.step", step=1):
        pass
    path = tracing.dump_trace(str(tmp_path))
    assert path is not None
    rc = igg_trace.main(["merge", str(tmp_path), "--device", "-o", "-"])
    tracing.reset()
    assert rc == 2
    assert "profile.p*.json" in capsys.readouterr().err


def test_igg_prof_attribute_run_dir(captured_run, capsys):
    assert igg_prof.main(
        ["attribute", captured_run["tdir"], "--json"]
    ) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["n_device_ops"] > 0
    assert rec["per_rank"]["0"]["n_device_ops"] == rec["n_device_ops"]


def test_attach_device_tracks_degrades_on_missing_host_track(captured_run):
    """A capture meta whose rank never dumped a host trace (crashed before
    dump_trace — the post-mortem case) degrades to a per-rank note; the
    surviving ranks' device-merged timeline still builds and validates."""
    doc = tracing.merge_trace_files([captured_run["host_trace"]])
    meta = json.load(
        open(profiling.find_capture_metas(captured_run["tdir"])[0])
    )
    orphan = dict(meta, rank=7)  # no such host track in the merged doc
    profiling.attach_device_tracks(doc, [meta, orphan])
    assert tracing.validate_chrome_trace(doc) == []
    per = doc["otherData"]["device_alignment"]["per_rank"]
    assert per["0"]["n_ops"] > 0  # the surviving rank attached fine
    assert per["7"]["n_ops"] == 0
    assert "no host track" in per["7"]["note"]


def test_maybe_arm_fires_once_per_process(monkeypatch, tmp_path):
    """The documented contract is 'the NEXT instrumented run': a process
    running several instrumented loops must not pay a profiler session
    per run / overwrite the first capture's artifacts (`reset()`
    re-arms)."""
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("IGG_PROFILE", "steps:50-60")  # never starts: cheap
    cap = profiling.maybe_arm(0)
    assert cap is not None
    assert profiling.maybe_arm(0) is None  # second run: already consumed
    profiling.reset()
    assert profiling.maybe_arm(0) is not None


def test_attach_device_tracks_refuses_stale_meta(captured_run):
    """The device twin of merge_trace_files' same-barrier refusal: a
    capture meta left by a PREVIOUS run (wall clock before this run's
    sync anchor) must be refused, not silently joined with a dead
    process's perf anchor."""
    doc = tracing.merge_trace_files([captured_run["host_trace"]])
    meta = json.load(
        open(profiling.find_capture_metas(captured_run["tdir"])[0])
    )
    meta["wall_start"] -= 3600.0  # a capture from an hour-older run
    with pytest.raises(ValueError, match="stale"):
        profiling.attach_device_tracks(doc, [meta])


def test_attribution_survives_archived_run_dir(captured_run, tmp_path, capsys):
    """Cross-round diffing works on a COPIED run dir: the meta's absolute
    trace_path/logdir are dead there, so resolution must fall back to the
    meta's own directory (`resolve_trace_path`)."""
    import shutil

    archived = tmp_path / "roundA"
    archived.mkdir()
    src = captured_run["tdir"]
    shutil.copy(
        profiling.find_capture_metas(src)[0],
        archived / "profile.p0.json",
    )
    shutil.copytree(os.path.join(src, "profile.p0"), archived / "profile.p0")
    # poison the recorded absolute locations: only the archive remains
    meta_path = str(archived / "profile.p0.json")
    meta = json.load(open(meta_path))
    meta["trace_path"] = "/nonexistent/run/trace.json.gz"
    meta["logdir"] = "/nonexistent/run"
    json.dump(meta, open(meta_path, "w"))
    assert igg_prof.main(["attribute", str(archived), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["n_device_ops"] > 0


def test_meta_lands_in_discoverable_base_dir_without_telemetry_dir(
    monkeypatch, tmp_path
):
    """IGG_PROFILE_DIR set, IGG_TELEMETRY_DIR unset: the meta must land in
    the BASE dir (where find_capture_metas globs), not nested inside the
    per-rank profile.p0/ capture dir."""
    caps = str(tmp_path / "caps")
    monkeypatch.setenv("IGG_PROFILE_DIR", caps)
    monkeypatch.delenv("IGG_TELEMETRY_DIR", raising=False)
    monkeypatch.setenv("IGG_PROFILE", "steps:1-1")
    tele.reset()
    cap = profiling.maybe_arm(0)
    assert cap is not None and cap.started
    import jax.numpy as jnp

    jax.jit(lambda a: a + 1)(jnp.ones((8,))).block_until_ready()
    cap.on_step(1)  # window [1,1] closes here
    assert cap.done
    metas = profiling.find_capture_metas(caps)
    assert len(metas) == 1 and metas[0].endswith("profile.p0.json")
    meta = json.load(open(metas[0]))
    assert meta["stopped_at_step"] == 1 and meta["reason"] == "window"


def test_close_records_last_completed_step(monkeypatch, tmp_path):
    """A scope-exit/run-complete stop records the LAST completed step, not
    the start step — the meta must not claim a 4-step capture covered
    one."""
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("IGG_PROFILE", "steps:2-10")
    tele.reset()
    cap = profiling.maybe_arm(0)
    for it in range(1, 6):  # run ends at step 5, window still open
        cap.on_step(it)
    assert cap.started
    cap.close("run_complete")
    meta = json.load(open(os.path.join(str(tmp_path), "profile.p0.json")))
    assert meta["started_at_step"] == 2
    assert meta["stopped_at_step"] == 5
    assert meta["reason"] == "run_complete"


# -- flight recorder + alias --------------------------------------------------


def test_flight_recorder_bundles_open_capture(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("IGG_PROFILE", "steps:1-100")
    tele.reset()
    cap = profiling.maybe_arm(0)  # window starts at step 1 immediately
    try:
        assert cap is not None and cap.started
        assert profiling.active_capture() == cap.info()
        path = tracing.dump_flight_recorder("test_crash", step=1)
        bundle = tracing.read_flight_bundles(path)[-1]
        assert bundle["profile"]["window"] == [1, 100]
        assert bundle["profile"]["started"] is True
        assert bundle["profile"]["logdir"].endswith("profile.p0")
    finally:
        profiling.close_open_capture("scope_exit")
    # the scope-exit stop landed the capture: meta written, reason recorded
    meta = json.load(open(os.path.join(str(tmp_path), "profile.p0.json")))
    assert meta["reason"] == "scope_exit"
    assert profiling.active_capture() is None


def test_profile_trace_alias_emits_parseable_capture(tmp_path):
    """Satellite: `igg.profile_trace` is the thin alias of the ONE capture
    implementation — its output must parse through the attribution
    pipeline (create_perfetto_trace now defaults on)."""
    import jax.numpy as jnp

    logdir = str(tmp_path / "alias")
    x = jnp.ones((64, 64))
    f = jax.jit(lambda a: a @ a)
    f(x).block_until_ready()
    with igg.profile_trace(logdir):
        f(x).block_until_ready()
    rec = profiling.attribute_capture(logdir)
    assert rec["n_device_ops"] > 0
    assert rec["device_seconds"] > 0
