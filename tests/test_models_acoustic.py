"""Acoustic staggered-grid FDTD model tests.

Oracle: decomposition invariance — the 8-device 2x2x2 run must match the
single-device run of the same global problem, including the staggered
(``n+1``-sized) velocity fields.
"""

import numpy as np
import pytest

import jax

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import acoustic3d

from tests.test_models_diffusion import dedup_global


def _run(nt, nx, devices=None, hide_comm=False):
    state, params = acoustic3d.setup(
        nx, nx, nx, devices=devices, hide_comm=hide_comm
    )
    gg = igg.get_global_grid()
    dims, o = gg.dims, gg.overlaps
    step = acoustic3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    out = {}
    names = ("P", "Vx", "Vy", "Vz")
    for name, A in zip(names, state):
        shp = igg.local_shape(A)
        ol = tuple(igg.ol(d, A) for d in range(3))
        g = np.asarray(igg.gather(A))
        out[name] = dedup_global(g, dims, shp, ol) if max(dims) > 1 else g
    igg.finalize_global_grid()
    return out


def test_staggered_multi_matches_single():
    nt, nx = 12, 10
    multi = _run(nt, nx)  # 2x2x2, global 18^3 (+1 staggered)
    single = _run(nt, 18, devices=[jax.devices()[0]])
    assert multi["P"].shape == (18, 18, 18)
    assert multi["Vx"].shape == (19, 18, 18)
    for k in multi:
        np.testing.assert_allclose(multi[k], single[k], rtol=1e-12, atol=1e-13, err_msg=k)


def test_hide_comm_matches_plain():
    nt, nx = 8, 10
    plain = _run(nt, nx)
    hidden = _run(nt, nx, hide_comm=True)
    for k in plain:
        np.testing.assert_allclose(hidden[k], plain[k], rtol=1e-12, atol=1e-13, err_msg=k)


def test_multi_step_matches_single_steps():
    nx = 10
    state, params = acoustic3d.setup(nx, nx, nx)
    step = acoustic3d.make_step(params, donate=False)
    multi = acoustic3d.make_multi_step(params, 6, donate=False)
    s1 = state
    for _ in range(6):
        s1 = jax.block_until_ready(step(*s1))
    s6 = jax.block_until_ready(multi(*state))
    for a, b in zip(s1, s6):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-12, atol=1e-13)
    igg.finalize_global_grid()


def test_wave_propagates_and_stays_bounded():
    state, params = acoustic3d.setup(12, 12, 12)
    P0 = np.asarray(igg.gather(acoustic3d.pressure(state)))
    step = acoustic3d.make_step(params)
    for _ in range(30):
        state = jax.block_until_ready(step(*state))
    P1 = np.asarray(igg.gather(acoustic3d.pressure(state)))
    igg.finalize_global_grid()
    assert P1.max() < P0.max()  # pulse spreads
    assert np.abs(P1).max() > 1e-6  # but is not lost
    assert np.isfinite(P1).all()


def test_exchange_cadence_matches_per_step():
    """w leapfrog steps + one width-w slab exchange of ALL fields (incl. the
    incrementally-updated P) must be bit-identical to the per-step path."""
    import numpy as np

    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True)
    state, params = acoustic3d.setup(10, 10, 10, **kw)
    step = acoustic3d.make_multi_step(params, 4, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = acoustic3d.setup(10, 10, 10, **kw)
    step2 = acoustic3d.make_multi_step(params, 4, donate=False, exchange_every=2)
    cad = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step2(*state))]
    igg.finalize_global_grid()
    for r, c in zip(ref, cad):
        np.testing.assert_array_equal(c, r)


@pytest.mark.parametrize("seed", range(3))
def test_random_overlap_staggered_invariance(seed):
    """Random overlaps with staggered fields: the multi-block run must match
    the single-device run of the same global problem exactly, with each
    field's shape-aware overlap (``ol = o + 1`` on its staggered axis)
    honored by the dedup."""
    rng = np.random.default_rng(8100 + seed)
    o = int(rng.integers(2, 5))
    nx = int(rng.integers(2 * o + 2, 2 * o + 5))
    nt = int(rng.integers(3, 7))
    okw = dict(overlapx=o, overlapy=o, overlapz=o)

    state, params = acoustic3d.setup(nx, nx, nx, quiet=True, **okw)
    gg = igg.get_global_grid()
    dims = gg.dims
    step = acoustic3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    multi = {}
    for name, A in zip(("P", "Vx", "Vy", "Vz"), state):
        shp = igg.local_shape(A)
        ol = tuple(igg.ol(d, A) for d in range(3))
        multi[name] = dedup_global(np.asarray(igg.gather(A)), dims, shp, ol)
    igg.finalize_global_grid()

    nxg = tuple(dims[d] * (nx - o) + o for d in range(3))
    state, params = acoustic3d.setup(
        *nxg, devices=[jax.devices()[0]], quiet=True
    )
    step = acoustic3d.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    for name, A in zip(("P", "Vx", "Vy", "Vz"), state):
        np.testing.assert_allclose(
            multi[name], np.asarray(igg.gather(A)), rtol=1e-12, atol=1e-13,
            err_msg=f"{name} o={o} nx={nx} nt={nt}",
        )
    igg.finalize_global_grid()


def test_fused_single_device_matches_xla():
    """fused_k on a no-halo-activity grid (1 device): the padded-layout
    staggered kernel chunk must match the per-step XLA path to few f32 ULPs
    (interpret-mode kernel)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    # dtype pinned: the suite runs x64, and f64 is outside the kernel
    # envelope (TPU Pallas has no 8-byte types) — without it this test
    # would silently exercise the XLA fallback instead of the kernel.
    kw = dict(devices=jax.devices()[:1], quiet=True, dtype=jax.numpy.float32)
    state, params = acoustic3d.setup(16, 32, 128, **kw)
    step = acoustic3d.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(A) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = acoustic3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = acoustic3d.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(A) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5)


def test_fused_deep_halo_matches_xla_multiblock():
    """Temporal blocking on a communicating STAGGERED grid: k fused kernel
    steps + one width-k all-field slab exchange vs the per-step path
    (interpret-mode kernel; deep halo overlapx=4 licenses fused_k=2).

    2 devices deliberately — the interpret-mode Pallas + shard_map deadlock
    constraint probed for the diffusion kernel applies here too."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1, overlapx=4, quiet=True,
        dtype=jax.numpy.float32,  # pinned: f64 is outside the kernel envelope
    )
    state, params = acoustic3d.setup(16, 32, 128, **kw)
    step = acoustic3d.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = acoustic3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = acoustic3d.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5)


def test_fused_fallback_warns_and_matches_xla():
    """A local block the kernel envelope rejects (y-size not a multiple of 8)
    must warn once and run the XLA path at the same all-field slab cadence —
    bit-identical to the per-step path at group boundaries."""
    # dtype pinned so the fallback fires for the documented y%8 shape
    # rejection, not the x64-itemsize check (the suite runs x64).
    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True,
              dtype=jax.numpy.float32)
    state, params = acoustic3d.setup(10, 10, 10, **kw)
    step = acoustic3d.make_multi_step(params, 4, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = acoustic3d.setup(10, 10, 10, **kw)
    with pytest.warns(RuntimeWarning, match="falling back to the XLA path"):
        stepf = acoustic3d.make_multi_step(params, 4, donate=False, fused_k=2)
        got = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g, r)


def test_fused_validation():
    state, params = acoustic3d.setup(
        16, 32, 128, devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1, quiet=True
    )
    with pytest.raises(ValueError, match="deep halo"):
        acoustic3d.make_multi_step(params, 4, fused_k=2)
    igg.finalize_global_grid()
    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True)
    state, params = acoustic3d.setup(10, 10, 10, **kw)
    with pytest.raises(ValueError, match="multiple of fused_k"):
        acoustic3d.make_multi_step(params, 5, fused_k=2)
    with pytest.raises(ValueError, match="pass both bx and by"):
        acoustic3d.make_multi_step(params, 4, fused_k=2, fused_tile=(8, None))
    with pytest.raises(ValueError, match="conflicts"):
        acoustic3d.make_multi_step(params, 4, fused_k=2, exchange_every=4)
    igg.finalize_global_grid()
    state, params = acoustic3d.setup(10, 10, 10, hide_comm=True, **kw)
    with pytest.raises(ValueError, match="mutually exclusive"):
        acoustic3d.make_multi_step(params, 4, fused_k=2)
    igg.finalize_global_grid()


def test_fused_zpatch_deep_halo_z_split_matches_xla():
    """The in-kernel z-slab cadence (z-dim decomposition): k fused kernel
    steps with VMEM-applied z patches + outside x/y exchange vs the
    per-step path (interpret-mode kernel, 2 devices split along z)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:2], dimx=1, dimy=1, dimz=2, overlapz=4, quiet=True,
        dtype=jax.numpy.float32,
    )
    state, params = acoustic3d.setup(16, 32, 128, **kw)
    step = acoustic3d.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = acoustic3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = acoustic3d.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("P", "Vx", "Vy", "Vz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)


def test_fused_zpatch_periodic_z_matches_xla():
    """Same cadence on the periodic self-neighbor z config (1 device,
    z-activity via the wrap — the degenerate config the hardware bench
    uses)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 4
    kw = dict(
        devices=jax.devices()[:1], periodz=1, overlapz=4, quiet=True,
        dtype=jax.numpy.float32,
    )
    state, params = acoustic3d.setup(16, 32, 128, **kw)
    step = acoustic3d.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(A) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = acoustic3d.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = acoustic3d.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(A) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("P", "Vx", "Vy", "Vz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)
