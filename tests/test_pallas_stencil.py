"""Tests for the temporally-blocked Pallas diffusion kernel.

The suite runs on the 8-virtual-CPU-device mesh (conftest), so the TPU
kernel executes under interpret mode (`utils.compat.pallas_force_interpret`) — the interpreter
implements the DMA/semaphore semantics, which is exactly what the kernel's
double-buffering logic needs validated.  Compiled-mode numbers come from
`bench.py` on the real chip (same code path minus the interpreter flag).

Oracle: ``fused_diffusion_steps(T, Cp, k)`` vs ``k`` applications of the
model's `_diffusion_update` — equal to a few float32 ULPs in the interior
(the two paths fold constants differently, see the module docstring), and
bit-exact on the frozen boundary ring.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from implicitglobalgrid_tpu.models.diffusion3d import Params, _diffusion_update
from implicitglobalgrid_tpu.ops.pallas_stencil import fused_diffusion_steps


def _setup(shape, seed=0):
    rng = np.random.default_rng(seed)
    T = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    Cp = jnp.asarray(1.0 + rng.random(shape), jnp.float32)
    dx = 0.1
    dt = dx * dx / 8.1
    params = Params(dx=dx, dy=dx, dz=dx, dt=dt, dtype=jnp.float32)
    c = float(dt / (dx * dx))
    return T, Cp, params, c


def _fused_interpret(T, Cp, k, c, **kw):
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    with pallas_force_interpret():
        return fused_diffusion_steps(T, Cp, k, c, c, c, **kw)


@pytest.mark.parametrize(
    "k,shape,tile",
    [
        (2, (16, 32, 128), dict(bx=8, by=16)),
        (4, (16, 32, 128), dict(bx=8, by=16)),
        (6, (32, 32, 128), dict(bx=8, by=16)),
        # minor dim spanning >1 lane tile (validated on hardware to n2=1024)
        (2, (16, 32, 384), dict(bx=8, by=16)),
        # k=8: in the envelope since round 5 (H=16 y-halo margin)
        (8, (32, 64, 128), dict(bx=8, by=16)),
    ],
)
def test_fused_matches_k_single_steps(k, shape, tile):
    T, Cp, params, c = _setup(shape)
    upd = jax.jit(_diffusion_update(params))
    ref = T
    for _ in range(k):
        ref = upd(ref, Cp)
    got = _fused_interpret(T, Cp, k, c, **tile)
    ref = np.asarray(jax.block_until_ready(ref))
    got = np.asarray(jax.block_until_ready(got))
    # Interior: few-ULP agreement (different constant folding, same math).
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    assert float(np.max(np.abs(got - ref))) < 5e-6
    # Frozen boundary ring: bit-exact (never touched by either path).
    T0 = np.asarray(T)
    for d in range(3):
        lo = np.take(got, 0, axis=d)
        hi = np.take(got, shape[d] - 1, axis=d)
        assert np.array_equal(lo, np.take(T0, 0, axis=d))
        assert np.array_equal(hi, np.take(T0, shape[d] - 1, axis=d))


def test_default_tile_shape():
    # The production default (bx=32, by=64, tuned on v5e) on a volume that
    # admits it.
    k = 2
    T, Cp, params, c = _setup((64, 128, 128))
    upd = jax.jit(_diffusion_update(params))
    ref = upd(upd(T, Cp), Cp)
    got = _fused_interpret(T, Cp, k, c)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_nonuniform_spacing_coefficients():
    # cx != cy != cz must reach the right axes.
    shape = (16, 32, 128)
    rng = np.random.default_rng(1)
    T = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    Cp = jnp.asarray(1.0 + rng.random(shape), jnp.float32)
    dx, dy, dz = 0.1, 0.2, 0.4
    dt = dx * dx / 8.1
    params = Params(dx=dx, dy=dy, dz=dz, dt=dt, dtype=jnp.float32)
    upd = jax.jit(_diffusion_update(params))
    ref = upd(upd(T, Cp), Cp)
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    with pallas_force_interpret():
        got = fused_diffusion_steps(
            T, Cp, 2,
            float(dt / (dx * dx)), float(dt / (dy * dy)), float(dt / (dz * dz)),
            bx=8, by=16,
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_bfloat16_structure():
    # bf16 runs with dtype-native rounding: the interior must agree with the
    # XLA bf16 path to bf16 accuracy (structural correctness; the two paths
    # round differently — minv multiply vs divide), and the frozen boundary
    # ring must stay bit-exact.  Hardware check (v5e, (64,128,256), k=2):
    # fused-vs-f32-ref error 0.32 vs XLA-bf16-vs-f32-ref 0.13 on O(1) data
    # scaled by O(100) Gaussians — same order, no corruption.
    k = 2
    shape = (16, 32, 128)
    rng = np.random.default_rng(3)
    T = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    Cp = jnp.asarray(1.0 + rng.random(shape), jnp.bfloat16)
    dx = 0.1
    dt = dx * dx / 8.1
    params = Params(dx=dx, dy=dx, dz=dx, dt=dt, dtype=jnp.bfloat16)
    c = float(dt / (dx * dx))
    upd = jax.jit(_diffusion_update(params))
    ref = np.asarray(upd(upd(T, Cp), Cp).astype(jnp.float32))
    got = np.asarray(_fused_interpret(T, Cp, k, c, bx=8, by=16).astype(jnp.float32))
    np.testing.assert_allclose(got, ref, atol=0.05, rtol=0.05)
    T0 = np.asarray(T.astype(jnp.float32))
    for ax in range(3):
        assert np.array_equal(np.take(got, 0, axis=ax), np.take(T0, 0, axis=ax))
        assert np.array_equal(
            np.take(got, shape[ax] - 1, axis=ax), np.take(T0, shape[ax] - 1, axis=ax)
        )


def test_auto_tile_fallback():
    # Volumes the tuned (32,64) tile does not fit fall back to smaller
    # candidates instead of raising (the old fixed default rejected them).
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        default_tile,
        fused_support_error,
    )

    # Full-y rungs lead when they fit (round 5: (32,n1) measured 976 vs
    # (32,64)'s 444 GB/s at 256^3 k=4 — no y halo, lowest recompute
    # redundancy).
    assert default_tile((64, 128, 128), 2) == (32, 128)
    assert default_tile((96, 96, 128), 2) == (32, 96)
    # Deep-z volumes where full-y busts VMEM fall onto the (32,128)
    # y-windowed rung (measured +6% over (32,64) at 512^3) — k <= 4 only:
    # the k=6 combination crashes the TPU compiler (probed), both in
    # auto-selection and as an explicit tile (the crash gate also disables
    # the full-y rungs there: by=n1 >= 128).
    assert default_tile((64, 256, 512), 4) == (32, 128)
    assert default_tile((64, 256, 512), 6) == (32, 64)
    err = fused_support_error((64, 256, 512), 6, 4, 32, 128)
    assert err is not None and "crashes the TPU compiler" in err
    assert default_tile((64, 128, 512), 4) == (32, 128)  # full-y fits here
    assert default_tile((32, 64, 128), 2) == (16, 64)   # full-y, bx=16
    assert default_tile((16, 32, 128), 2) == (8, 16)  # too small for 16x32 halos
    assert default_tile((8, 8, 128), 2) is None
    # End-to-end: auto-picked tile matches k XLA steps.
    k = 2
    T, Cp, params, c = _setup((32, 64, 128))
    upd = jax.jit(_diffusion_update(params))
    ref = upd(upd(T, Cp), Cp)
    got = _fused_interpret(T, Cp, k, c)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_z_export_lane_layout():
    """White-box pin of the z-export lane contract (round 4): lanes [0,k) =
    post-step planes [n2-o, n2-o+k) (send-hi), [k,2k) = planes [o-k, o)
    (send-lo), [2k,3k) = current planes [0,k), [3k,4k) = planes [n2-k,n2) —
    the layout `ops.halo.z_patch_from_export` communicates."""
    k, o = 2, 4
    shape = (16, 32, 128)
    T, Cp, params, c = _setup(shape)
    from implicitglobalgrid_tpu.ops.halo import _pack_z_patch

    # Identity patch (re-writes the current z planes — a no-op application).
    patch = _pack_z_patch(T[:, :, 0:k], T[:, :, -k:], k)
    T_ref = _fused_interpret(T, Cp, k, c, bx=8, by=16)
    T_got, zex = _fused_interpret(
        T, Cp, k, c, bx=8, by=16, z_patch=patch, z_export=True, z_overlap=o
    )
    np.testing.assert_allclose(np.asarray(T_got), np.asarray(T_ref), rtol=2e-6, atol=2e-6)
    zex = np.asarray(zex)
    Tg = np.asarray(T_got)
    n2 = shape[2]
    np.testing.assert_array_equal(zex[:, :, 0:k], Tg[:, :, n2 - o : n2 - o + k])
    np.testing.assert_array_equal(zex[:, :, k : 2 * k], Tg[:, :, o - k : o])
    np.testing.assert_array_equal(zex[:, :, 2 * k : 3 * k], Tg[:, :, 0:k])
    np.testing.assert_array_equal(zex[:, :, 3 * k : 4 * k], Tg[:, :, n2 - k : n2])


def test_z_export_validation():
    k = 2
    T, Cp, params, c = _setup((16, 32, 128))
    from implicitglobalgrid_tpu.ops.halo import _pack_z_patch

    patch = _pack_z_patch(T[:, :, 0:k], T[:, :, -k:], k)
    with pytest.raises(ValueError, match="z_export requires z_patch"):
        fused_diffusion_steps(T, Cp, k, c, c, c, z_export=True, z_overlap=4)
    with pytest.raises(ValueError, match="2k <= o <= n2/2"):
        fused_diffusion_steps(
            T, Cp, k, c, c, c, z_patch=patch, z_export=True, z_overlap=2
        )


def test_vmem_budget_env_override(monkeypatch):
    """IGG_VMEM_MB (per-core VMEM capacity) re-tunes every kernel envelope
    without editing source (VERDICT r3 #6: the budgets were v5e-tuned module
    constants with no adjustment path for other generations).  The declared
    capacity scales each kernel's budget proportionally, preserving the
    per-kernel headroom ratios."""
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        default_tile,
        fused_support_error,
    )

    # A 1024-deep volume: the (16,128) full-y rung estimates ~52.4 MiB —
    # inside the 59.5 MiB default (the budget is an ESTIMATE bound; Mosaic's
    # real ~1.85x overshoot is what the 59.5 encodes); the (32,128) full-y
    # rung (~94 MiB) is out.
    assert default_tile((64, 128, 1024), 2) == (16, 128)
    monkeypatch.setenv("IGG_VMEM_MB", "64")
    # Half the tuned capacity: budget ~29.8 MiB, auto-selection degrades and
    # oversized explicit tiles are rejected with the override in the message.
    assert default_tile((64, 128, 1024), 2) == (16, 32)
    err = fused_support_error((64, 128, 1024), 2, 4, 32, 64)
    assert err is not None and "IGG_VMEM_MB" in err
    monkeypatch.setenv("IGG_VMEM_MB", "256")
    # Doubled capacity re-admits the (32,128) full-y rung.
    assert default_tile((64, 128, 1024), 2) == (32, 128)
    for bad in ("nope", "0", "-5"):
        monkeypatch.setenv("IGG_VMEM_MB", bad)
        with pytest.raises(ValueError, match="IGG_VMEM_MB"):
            default_tile((64, 128, 1024), 2)


def test_validation_errors():
    T, Cp, params, c = _setup((16, 32, 128))
    with pytest.raises(ValueError, match="k must be even"):
        fused_diffusion_steps(T, Cp, 3, c, c, c)
    with pytest.raises(ValueError, match="k must be even"):
        # k=8 is IN the envelope since round 5 (H=16 margin); 10 is out.
        fused_diffusion_steps(T, Cp, 10, c, c, c)
    with pytest.raises(ValueError, match="does not divide"):
        fused_diffusion_steps(T, Cp, 2, c, c, c, bx=7, by=16)
    with pytest.raises(ValueError, match="minor dimension"):
        big = jnp.zeros((16, 32, 2048), jnp.float32)
        fused_diffusion_steps(big, jnp.ones_like(big), 2, c, c, c, bx=8, by=16)
    with pytest.raises(ValueError, match="VMEM"):
        wide = jnp.zeros((256, 256, 1024), jnp.float32)
        fused_diffusion_steps(wide, jnp.ones_like(wide), 2, c, c, c, bx=128, by=128)
    with pytest.raises(ValueError, match="share a dtype"):
        fused_diffusion_steps(T, Cp.astype(jnp.bfloat16), 2, c, c, c, bx=8, by=16)
