"""The self-healing run supervisor (ISSUE 14; docs/robustness.md).

Four surfaces, each pinned at the unit level plus one real end-to-end
supervision loop over fake workers:

* generation fencing — publish/read round-trip, monotonicity, and the
  acceptance contract: a process carrying a STALE generation token is
  refused at `save_checkpoint` / the resize publish / the endpoint-file
  write, and every refusal lands as a rank-tagged ``fence.rejected``
  telemetry event;
* failure classification — the pure evidence -> class matrix;
* the recovery-policy engine — restart strikes, shrink, scale-up,
  quarantine, give-up, deterministic backoff; and `recovery_plan`'s
  rank-invariance as censused by the ``collective-consistency`` analyzer
  (with the seeded POSITIVE divergence fixture);
* the chaos plane — seeded `chaos_schedule` determinism, spec expansion /
  round-trip, the ``net_delay`` kind, and the supervisor's fired-fault
  pruning.

The real 2-process gloo storm lives in ``scripts/soak.py chaos --quick``
(docs/testing.md); these tests keep the machinery pinned in tier-1.
"""

import os
import sys
import time
import types

import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu import supervisor as sup
from implicitglobalgrid_tpu.supervisor import generation as gen_mod
from implicitglobalgrid_tpu.utils import checkpoint as ckpt
from implicitglobalgrid_tpu.utils import resilience as res
from implicitglobalgrid_tpu.utils import telemetry as tele
from implicitglobalgrid_tpu.utils import tracing

NX = 8


@pytest.fixture
def clean_env(monkeypatch):
    for k in list(os.environ):
        if k.startswith("IGG_"):
            monkeypatch.delenv(k)
    res.reset_fault_injector()
    tele.reset()
    yield monkeypatch
    res.reset_fault_injector()
    tele.reset()


def _events(path):
    return tele.read_events(path)


# -- generation tokens + fencing ----------------------------------------------


def test_generation_publish_read_roundtrip(clean_env, tmp_path):
    d = str(tmp_path)
    assert gen_mod.authoritative_generation(d) is None
    gen_mod.publish_generation(3, d, reason="test")
    assert gen_mod.authoritative_generation(d) == 3
    gen_mod.publish_generation(3, d)  # same token republishes fine
    with pytest.raises(ValueError, match="monotonic"):
        gen_mod.publish_generation(2, d)
    assert gen_mod.authoritative_generation(d) == 3


def test_corrupt_fence_file_reads_absent_but_is_evented(clean_env, tmp_path):
    """A torn/corrupt generation.json must not wedge the run (it reads as
    "no fence"), but because that state disarms zombie refusal it has to
    land on the timeline — unlike a genuinely absent file, which is the
    normal unsupervised case and stays silent."""
    telem = tmp_path / "telemetry"
    clean_env.setenv("IGG_TELEMETRY", "1")
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    assert gen_mod.authoritative_generation(str(tmp_path)) is None
    assert "fence.corrupt_total" not in tele.snapshot()["counters"]
    (tmp_path / gen_mod.GENERATION_FILE).write_text("{torn mid-write")
    assert gen_mod.authoritative_generation(str(tmp_path)) is None
    assert tele.snapshot()["counters"]["fence.corrupt_total"] == 1
    events = _events(telem / "events.jsonl")
    corrupt = [x for x in events if x["type"] == "fence.corrupt"]
    assert corrupt and corrupt[0]["path"].endswith(gen_mod.GENERATION_FILE)


def test_unfenced_process_never_refused(clean_env, tmp_path):
    # no IGG_GENERATION: every check passes whatever the fence file says
    gen_mod.publish_generation(9, str(tmp_path))
    clean_env.setenv("IGG_FENCE_DIR", str(tmp_path))
    assert gen_mod.fence_refusal("checkpoint.save") is None
    gen_mod.check_fence("checkpoint.save")  # no raise


def test_stale_token_refused_with_rank_tagged_event(clean_env, tmp_path):
    fence = tmp_path / "fence"
    telem = tmp_path / "telemetry"
    gen_mod.publish_generation(2, str(fence))
    clean_env.setenv("IGG_FENCE_DIR", str(fence))
    clean_env.setenv("IGG_GENERATION", "1")
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    with pytest.raises(gen_mod.FenceError) as e:
        gen_mod.check_fence("checkpoint.save")
    assert e.value.generation == 1 and e.value.authoritative == 2
    events = _events(telem / "events.jsonl")
    rej = [x for x in events if x["type"] == "fence.rejected"]
    assert rej and rej[0]["what"] == "checkpoint.save"
    assert rej[0]["generation"] == 1 and rej[0]["authoritative"] == 2
    assert "rank" in rej[0]
    assert rej[0]["gen"] == 1  # the event itself carries the stale token
    assert tele.snapshot()["counters"]["fence.rejected_total"] == 1


def test_current_token_passes_fence(clean_env, tmp_path):
    gen_mod.publish_generation(2, str(tmp_path))
    clean_env.setenv("IGG_FENCE_DIR", str(tmp_path))
    clean_env.setenv("IGG_GENERATION", "2")
    assert not gen_mod.fence_refused("anything")


def test_save_checkpoint_fenced_and_meta_carries_generation(
    clean_env, tmp_path
):
    fence = tmp_path / "fence"
    telem = tmp_path / "telemetry"
    ckdir = tmp_path / "ckpt"
    clean_env.setenv("IGG_FENCE_DIR", str(fence))
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    clean_env.setenv("IGG_GENERATION", "1")
    gen_mod.publish_generation(1, str(fence))
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.zeros((NX, NX, NX))
    # current generation: the save succeeds and records its token
    path = ckpt.save_checkpoint(ckdir, (T,), 2)
    assert ckpt.checkpoint_meta(path)["generation"] == 1
    # the supervisor moves on; the zombie's next publish is REFUSED
    gen_mod.publish_generation(2, str(fence))
    with pytest.raises(gen_mod.FenceError):
        ckpt.save_checkpoint(ckdir, (T,), 4)
    assert ckpt.latest_checkpoint(ckdir) == path  # nothing new published
    rej = [
        x for x in _events(telem / "events.jsonl")
        if x["type"] == "fence.rejected"
    ]
    assert rej and rej[-1]["what"] == "checkpoint.save"
    assert "rank" in rej[-1]


def test_liveplane_endpoint_write_fenced(clean_env, tmp_path):
    from implicitglobalgrid_tpu.utils import liveplane

    fence = tmp_path / "fence"
    telem = tmp_path / "telemetry"
    clean_env.setenv("IGG_FENCE_DIR", str(fence))
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    clean_env.setenv("IGG_GENERATION", "0")
    gen_mod.publish_generation(1, str(fence))
    server = types.SimpleNamespace(host="127.0.0.1", port=12345)
    liveplane._publish_endpoint(server)
    assert not os.path.isfile(telem / liveplane.endpoint_filename(0))
    rej = [
        x for x in _events(telem / "events.jsonl")
        if x["type"] == "fence.rejected"
    ]
    assert rej and rej[0]["what"] == "liveplane.endpoint"


def test_frontdoor_resize_publish_fenced(clean_env, tmp_path):
    from implicitglobalgrid_tpu.serving.frontdoor import FrontDoor

    fence = tmp_path / "fence"
    telem = tmp_path / "telemetry"
    clean_env.setenv("IGG_FENCE_DIR", str(fence))
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    clean_env.setenv("IGG_GENERATION", "0")
    gen_mod.publish_generation(1, str(fence))
    fd = FrontDoor.__new__(FrontDoor)  # the fence gate precedes any state
    with pytest.raises(gen_mod.FenceError):
        fd._execute_resize({"nproc": 2, "capacity": 4, "rung": 1})
    rej = [
        x for x in _events(telem / "events.jsonl")
        if x["type"] == "fence.rejected"
    ]
    assert rej and rej[0]["what"] == "frontdoor.resize"


def test_frontdoor_control_broadcast_generation_mismatch_refused(
    clean_env, tmp_path
):
    from implicitglobalgrid_tpu.serving.frontdoor import FrontDoor

    telem = tmp_path / "telemetry"
    clean_env.setenv("IGG_TELEMETRY_DIR", str(telem))
    clean_env.setenv("IGG_GENERATION", "2")
    fd = FrontDoor.__new__(FrontDoor)
    assert fd._apply({"gen": 1, "shutdown": True}) is None  # refused whole
    rej = [
        x for x in _events(telem / "events.jsonl")
        if x["type"] == "fence.rejected"
    ]
    assert rej and rej[0]["what"] == "frontdoor.control"
    # a matching stamp applies normally
    assert fd._apply({"gen": 2, "shutdown": True}) == "shutdown"


def test_event_lines_carry_generation_tag(clean_env, tmp_path):
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tele.event("x")  # unfenced: no gen key
    clean_env.setenv("IGG_GENERATION", "5")
    tele.event("y")
    events = {e["type"]: e for e in _events(tmp_path / "events.jsonl")}
    assert "gen" not in events["x"]
    assert events["y"]["gen"] == 5


# -- checkpoint fallback-depth gauge (satellite) ------------------------------


def test_latest_checkpoint_publishes_fallback_depth(clean_env, tmp_path):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.zeros((NX, NX, NX))
    ckdir = tmp_path / "ckpt"
    p2 = ckpt.save_checkpoint(ckdir, (T,), 2)
    p4 = ckpt.save_checkpoint(ckdir, (T,), 4)
    assert ckpt.latest_checkpoint(ckdir) == p4
    assert tele.gauge_value("checkpoint.fallback_depth") == 0
    # damage the newest generation: the walk must skip it AND publish how
    # far it limped back
    shard = os.path.join(p4, "shards_p0.npz")
    with open(shard, "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    assert ckpt.latest_checkpoint(ckdir) == p2
    assert tele.gauge_value("checkpoint.fallback_depth") == 1


def test_fallback_depth_event_emitted(clean_env, tmp_path, monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path / "telemetry"))
    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.zeros((NX, NX, NX))
    ckdir = tmp_path / "ckpt"
    p2 = ckpt.save_checkpoint(ckdir, (T,), 2)
    p4 = ckpt.save_checkpoint(ckdir, (T,), 4)
    os.remove(os.path.join(p4, "shards_p0.npz"))
    assert ckpt.latest_checkpoint(ckdir) == p2
    depth = [
        e for e in _events(tmp_path / "telemetry" / "events.jsonl")
        if e["type"] == "checkpoint.fallback_depth"
    ]
    assert depth and depth[-1]["depth"] == 1 and "rank" in depth[-1]


# -- failure classification ---------------------------------------------------


def _ev(kind, **kw):
    return {"type": kind, "ts": time.time(), "rank": kw.pop("rank", 0), **kw}


def test_exit_status_constants_agree():
    """The host-only classifier keeps a literal RESIZE_STATUS (importing
    the serving package would pull the model zoo in); this pin ties every
    copy to its canonical definition."""
    from implicitglobalgrid_tpu.serving.frontdoor import RESIZE_STATUS
    from implicitglobalgrid_tpu.supervisor import classify as classify_fn  # noqa: F401
    from implicitglobalgrid_tpu.supervisor.classify import (
        CRASH_STATUS as SUP_CRASH,
        RESIZE_STATUS as SUP_RESIZE,
    )

    assert SUP_CRASH == res.FaultInjector.CRASH_STATUS == 17
    assert SUP_RESIZE == RESIZE_STATUS == 19


def test_classify_matrix():
    C = sup.classify
    assert C([0, 0]).kind == "healthy"
    assert C([19, 19]).kind == "resize"
    assert C([0, 17]).kind == "crash"
    assert C([0, 17]).detail.get("injected") is True
    assert C([1, 0]).ranks == (0,)
    # mixed resize is a failed broadcast, not a resize
    mixed = C([0, 19])
    assert mixed.kind == "crash" and mixed.detail["mixed_resize"] is True


def test_classify_specific_bundles_win():
    ev = {"bundles": {1: [_ev(None, reason="gather_tripwire")]},
          "alerts": [], "events": []}
    inc = sup.classify([0, 1], ev)
    assert inc.kind == "gather_tripwire"
    assert inc.detail["bundle_reason"] == "gather_tripwire"
    ev = {"bundles": {0: [_ev(None, reason="guard.trip")]},
          "alerts": [], "events": []}
    assert sup.classify([1, 0], ev).kind == "guard_trip"
    ev = {"bundles": {0: [_ev(None, reason="watchdog.deadline_exceeded")]},
          "alerts": [], "events": []}
    assert sup.classify([None, None], ev).kind == "step_stall"


def test_classify_clean_exit_demotes_recovered_bundles_to_detail():
    """A guard trip whose rollback SUCCEEDED (all ranks exited 0) left a
    flight bundle — classifying it as a failure would restart a finished
    job, so it must ride as detail on a healthy incident."""
    ev = {"bundles": {0: [_ev(None, reason="guard.trip")]},
          "alerts": [], "events": []}
    inc = sup.classify([0, 0], ev)
    assert inc.kind == "healthy" and not inc.failed
    assert inc.detail["bundle_reason"] == "guard.trip"
    # same for a blown watchdog deadline the loop outlived, on a resize
    ev = {"bundles": {1: [_ev(None, reason="watchdog.deadline_exceeded")]},
          "alerts": [], "events": []}
    assert sup.classify([19, 19], ev).kind == "resize"


def test_classify_sigkilled_ranks_count_as_killed():
    """The manager's grace/timeout reap delivers rc=-9 (SIGKILL), which
    must satisfy the killed-not-crashed contract the stall/straggler
    classes key on — the supervisor's real kill path, not just the
    synthetic rc=None evidence."""
    stall = _ev("alert.step_stall", rank=1)
    ev = {"bundles": {}, "alerts": [stall], "events": [stall]}
    assert sup.classify([-9, -9], ev).kind == "step_stall"
    skew = _ev("skew.straggler", rank=1)
    ev = {"bundles": {}, "alerts": [], "events": [skew]}
    assert sup.classify([-9, None], ev).kind == "straggler"
    # a rank that died of a real signal (segfault) is still a crash
    ev = {"bundles": {}, "alerts": [stall], "events": [stall]}
    assert sup.classify([-11, -9], ev).kind == "crash"


def test_classify_corrupt_checkpoint_and_stall_and_straggler():
    ckpt_ev = _ev("checkpoint.fallback", problem="shard corrupt")
    ev = {"bundles": {}, "alerts": [], "events": [ckpt_ev]}
    assert sup.classify([17, 0], ev).kind == "corrupt_checkpoint"
    stall = _ev("alert.step_stall", rank=1)
    ev = {"bundles": {}, "alerts": [stall], "events": [stall]}
    # killed-while-wedged = stall; a clean exit demotes it to detail
    assert sup.classify([None, None], ev).kind == "step_stall"
    clean = sup.classify([0, 0], ev)
    assert clean.kind == "healthy"
    assert clean.detail["transient_alerts"] == ["alert.step_stall"]
    assert sup.classify([19, 19], ev).kind == "resize"
    skew = _ev("skew.straggler", rank=1)
    ev = {"bundles": {}, "alerts": [], "events": [skew]}
    assert sup.classify([None, None], ev).kind == "straggler"


def test_classify_suspect_ranks_follow_the_evidence_not_the_exits():
    """Quarantine must target the rank the integrity evidence names — a
    corrupting rank can take innocent peers down with it."""
    # the damaged shard file names its WRITER rank (rank 0), even though
    # the rank that died was rank 1
    ckpt_ev = _ev(
        "checkpoint.fallback",
        problem="shard shards_p0.npz corrupt: CRC32 0x1 on disk vs 0x2",
    )
    ev = {"bundles": {}, "alerts": [], "events": [ckpt_ev]}
    inc = sup.classify([0, 17], ev)
    assert inc.kind == "corrupt_checkpoint" and inc.ranks == (0,)
    assert inc.rcs == (0, 17)  # the exit picture stays visible
    # a flight bundle's own rank is the implicated one likewise
    ev = {"bundles": {0: [_ev(None, reason="gather_tripwire")]},
          "alerts": [], "events": []}
    assert sup.classify([0, 1], ev).ranks == (0,)


def test_classify_since_ts_filters_previous_incarnations():
    old = dict(_ev("checkpoint.fallback"), ts=100.0)
    ev = {"bundles": {}, "alerts": [], "events": [old]}
    assert sup.classify([17, 0], ev, since_ts=200.0).kind == "crash"
    assert sup.classify([17, 0], ev, since_ts=50.0).kind == "corrupt_checkpoint"


def test_collect_evidence_reads_bundles_and_alerts(tmp_path, clean_env):
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    tele.event("alert.step_stall", severity="critical")
    tracing.dump_flight_recorder("gather_tripwire", round=2)
    ev = sup.collect_evidence(str(tmp_path))
    assert 0 in ev["bundles"]
    assert ev["bundles"][0][-1]["reason"] == "gather_tripwire"
    assert [a["type"] for a in ev["alerts"]] == ["alert.step_stall"]
    assert sup.collect_evidence(str(tmp_path / "missing")) == {
        "bundles": {}, "alerts": [], "events": []
    }


def test_collect_evidence_incremental_offsets(tmp_path, clean_env):
    """The supervisor's offset map makes each collection parse only the
    lines appended since the previous one (a long run's shared telemetry
    history must not be re-read per incident)."""
    clean_env.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    offsets: dict = {}
    tele.event("fault.worker_crash", step=2)
    ev1 = sup.collect_evidence(str(tmp_path), offsets=offsets)
    assert [e["type"] for e in ev1["events"]] == ["fault.worker_crash"]
    tele.event("alert.step_stall", severity="critical")
    ev2 = sup.collect_evidence(str(tmp_path), offsets=offsets)
    assert [e["type"] for e in ev2["events"]] == ["alert.step_stall"]
    assert [a["type"] for a in ev2["alerts"]] == ["alert.step_stall"]
    # nothing new -> nothing parsed; a torn trailing line is NOT consumed
    assert sup.collect_evidence(str(tmp_path), offsets=offsets)["events"] == []
    path = tmp_path / "events.jsonl"
    with open(path, "a") as f:
        f.write('{"type": "fault.stall", "ts": 1.0, "rank": 0}')  # no \n
    assert sup.collect_evidence(str(tmp_path), offsets=offsets)["events"] == []
    with open(path, "a") as f:
        f.write("\n")
    got = sup.collect_evidence(str(tmp_path), offsets=offsets)["events"]
    assert [e["type"] for e in got] == ["fault.stall"]


# -- recovery policy ----------------------------------------------------------


def test_policy_restart_then_shrink_then_give_up():
    pol = sup.RecoveryPolicy(max_restarts=2, backoff_s=0.01)
    st = sup.SupervisorState()
    crash = sup.Incident(kind="crash", ranks=(1,), rcs=(0, 17), detail={})
    for i in range(2):
        d = sup.decide(crash, st, pol, ladder_len=2)
        assert d.action == "restart" and d.rung == 0, (i, d)
        assert d.delay_s > 0
        st.apply(d)
    d = sup.decide(crash, st, pol, ladder_len=2)
    assert d.action == "shrink" and d.rung == 1
    assert "IGG_SUPERVISE_MAX_RESTARTS" in d.reason
    st.apply(d)
    assert st.restarts == 0  # a shrink resets the streak
    d = sup.decide(crash, st, pol, ladder_len=2)
    assert d.action == "restart"  # fresh strikes at the new rung
    st.apply(d)
    st.apply(sup.decide(crash, st, pol, ladder_len=2))
    d = sup.decide(crash, st, pol, ladder_len=2)
    assert d.action == "give_up"


def test_policy_healthy_and_scale_up():
    pol = sup.RecoveryPolicy(max_restarts=1, backoff_s=0.01, scale_up_after=2)
    healthy = sup.Incident(kind="healthy", ranks=(), rcs=(0,), detail={})
    st = sup.SupervisorState(rung=1)
    d = sup.decide(healthy, st, pol, ladder_len=2)
    assert d.action == "none"  # streak 1 < scale_up_after
    st.apply(d)
    d = sup.decide(healthy, st, pol, ladder_len=2)
    assert d.action == "scale_up" and d.rung == 0
    # at the preferred rung, healthy is just healthy
    st = sup.SupervisorState(rung=0)
    assert sup.decide(healthy, st, pol, ladder_len=2).action == "none"


def test_policy_quarantine_after_repeated_integrity_failures():
    pol = sup.RecoveryPolicy(max_restarts=0, backoff_s=0.01,
                             quarantine_after=2)
    st = sup.SupervisorState()
    inc = sup.Incident(kind="gather_tripwire", ranks=(1,), rcs=(0, 1),
                       detail={})
    # the manager's sequence: record the incident, THEN decide — strikes
    # accumulate across incarnations in the state, not per decision
    st.record_incident(inc)
    d1 = sup.decide(inc, st, pol, ladder_len=3)
    assert d1.action == "shrink"  # strike 1: no quarantine yet
    assert st.suspect_strikes == {1: 1}
    st.apply(d1)
    st.record_incident(inc)
    d2 = sup.decide(inc, st, pol, ladder_len=3)
    assert d2.action == "quarantine" and d2.quarantined == (1,)
    st.apply(d2)
    assert 1 in st.quarantined
    # no smaller rung left -> give_up carrying the quarantine verdict
    st2 = sup.SupervisorState(rung=2, suspect_strikes={1: 2})
    d3 = sup.decide(inc, st2, pol, ladder_len=3)
    assert d3.action == "give_up" and d3.quarantined == (1,)
    # a transient incident charges no strikes
    st3 = sup.SupervisorState()
    st3.record_incident(sup.Incident(kind="crash", ranks=(0,), rcs=(1,),
                                     detail={}))
    assert st3.suspect_strikes == {}


def test_policy_decide_is_deterministic_and_env_tier(clean_env):
    pol = sup.RecoveryPolicy(max_restarts=1, backoff_s=0.25, seed=3)
    st = sup.SupervisorState()
    crash = sup.Incident(kind="crash", ranks=(0,), rcs=(1,), detail={})
    d1 = sup.decide(crash, st, pol, ladder_len=2)
    d2 = sup.decide(crash, st, pol, ladder_len=2)
    assert d1 == d2
    clean_env.setenv("IGG_SUPERVISE_MAX_RESTARTS", "7")
    clean_env.setenv("IGG_SUPERVISE_BACKOFF_S", "0.125")
    pol = sup.RecoveryPolicy.from_env()
    assert pol.max_restarts == 7 and pol.backoff_s == 0.125
    assert sup.RecoveryPolicy.from_env(max_restarts=1).max_restarts == 1


def test_recovery_plan_rank_and_fence_invariance():
    for action in sup.ACTIONS:
        assert sup.recovery_plan(True, action, False) == sup.recovery_plan(
            False, action, False
        )
        # a stale incarnation refuses the directive on EVERY rank together
        assert sup.recovery_plan(True, action, True) == ()
    plan = sup.recovery_plan(False, "resize", False)
    assert plan[0] == ("broadcast_control", "directive")
    assert sum(1 for op in plan if op[0] == "save_checkpoint") == 2
    assert sup.recovery_plan(True, "restart", False) == ()


# -- the collective-consistency census (CI/tooling satellite) -----------------


def test_supervisor_census_registered_and_consistent():
    from implicitglobalgrid_tpu.analysis import collectives as coll

    assert coll.supervisor_plan_censuses in coll.CENSUS_PROVIDERS
    censuses = list(coll.supervisor_plan_censuses(None))
    assert len(censuses) == 2 * len(sup.ACTIONS)
    for census in censuses:
        assert coll.check_rank_consistency(census) == [], census.name


def test_supervisor_census_catches_rank_keyed_recovery_decision():
    """The seeded POSITIVE fixture: a recovery plan keyed on rank-local
    fence state (one stale rank skipping the checkpoint barriers its
    peers enter) is exactly the deadlock class the detector pins."""
    from implicitglobalgrid_tpu.analysis import collectives as coll
    from implicitglobalgrid_tpu.analysis.ir import RankCensus

    def broken_plan(rank):
        # rank 1 thinks it is fenced and refuses; everyone else proceeds
        return sup.recovery_plan(rank == 0, "resize", stale=(rank == 1))

    census = RankCensus(
        name="host/supervisor_recovery[broken-rank-keyed-fence]",
        sequences={rank: broken_plan(rank) for rank in range(4)},
    )
    findings = coll.check_rank_consistency(census)
    assert findings and findings[0].severity == "CRITICAL"
    assert findings[0].code == "rank-divergent-sequence"


# -- the chaos plane ----------------------------------------------------------


def test_chaos_schedule_deterministic_and_bounded():
    a = res.chaos_schedule(11, 0.5, steps=20)
    assert a == res.chaos_schedule(11, 0.5, steps=20)
    steps_seen = [int(s.split(":step")[1]) for s in a]
    assert steps_seen == sorted(steps_seen)
    assert len(set(steps_seen)) == len(steps_seen)  # <= one fault per step
    assert all(s.split(":")[0] in res.CHAOS_KINDS for s in a)
    assert res.chaos_schedule(11, 0.0, steps=20) == []
    with pytest.raises(ValueError, match="rate"):
        res.chaos_schedule(1, 1.5)
    with pytest.raises(ValueError, match="steps"):
        res.chaos_schedule(1, 0.5, steps=0)
    with pytest.raises(ValueError, match="init_flake"):
        res.chaos_schedule(1, 0.5, kinds=("init_flake",))


def test_chaos_spec_parses_into_fault_set(clean_env):
    fs = res.FaultSet.from_spec("chaos:seed=3:rate=0.7:steps=10")
    assert fs.specs() == res.chaos_schedule(3, 0.7, steps=10)
    fs2 = res.FaultSet.from_spec(
        "chaos:seed=3:rate=0.7:steps=10:kinds=stall+net_delay"
    )
    assert all(s.split(":")[0] in ("stall", "net_delay") for s in fs2.specs())
    # chaos composes with explicit faults, comma-separated
    fs3 = res.FaultSet.from_spec(
        "worker_crash:step4:proc1,chaos:seed=3:rate=0.3:steps=4"
    )
    assert fs3.specs()[0] == "worker_crash:step4:proc1"
    with pytest.raises(ValueError, match="chaos"):
        res.FaultSet.from_spec("chaos:seed=x:rate=0.5")
    with pytest.raises(ValueError, match="chaos"):
        res.FaultSet.from_spec("chaos:rate=0.5")
    with pytest.raises(ValueError, match="chaos"):
        res.FaultSet.from_spec("chaos:seed=1:rate=0.5:bogus=2")


def test_fault_spec_roundtrip_and_event_matching():
    for spec in ("worker_crash:step4:proc1", "net_delay:step2",
                 "ckpt_corrupt:step6:shard1", "init_flake:2"):
        assert res.FaultInjector.from_spec(spec).spec() == spec
    fired = [
        {"type": "fault.worker_crash", "step": 4},
        {"type": "fault.net_delay", "step": 2},
        {"type": "fault.init_flake", "remaining": 1},
    ]
    assert res.fault_event_matches_spec(fired, "worker_crash:step4:proc1")
    assert res.fault_event_matches_spec(fired, "net_delay:step2")
    assert res.fault_event_matches_spec(fired, "init_flake:2")
    assert not res.fault_event_matches_spec(fired, "worker_crash:step5")
    assert not res.fault_event_matches_spec(fired, "stall:step4")


def test_net_delay_arms_the_collective_delay_hook(clean_env):
    inj = res.FaultInjector.from_spec("net_delay:step3:proc0")
    inj.maybe_net_delay(2)
    assert tracing._collective_delay == 0.0
    t0 = time.perf_counter()
    inj.maybe_net_delay(3)
    assert inj.fired
    assert tracing._collective_delay == pytest.approx(inj.NET_DELAY_S)
    # arming is instant — the latency lands in the next host collective
    assert time.perf_counter() - t0 < 1.0
    tracing.arm_collective_delay(0.01)
    t0 = time.perf_counter()
    tracing._consume_collective_delay()
    assert time.perf_counter() - t0 >= 0.01
    assert tracing._collective_delay == 0.0
    tracing.reset()


# -- RunSupervisor end to end (fake workers, no jax) --------------------------


_FAKE_WORKER = r"""
import json, os, sys, time
gen = int(os.environ["IGG_GENERATION"])
rank = int(sys.argv[1])
tele = os.environ["IGG_TELEMETRY_DIR"]
os.makedirs(tele, exist_ok=True)
def event(etype, **kw):
    rec = {"ts": time.time(), "type": etype, "rank": rank, "gen": gen, **kw}
    name = "events.jsonl" if rank == 0 else f"events.p{rank}.jsonl"
    with open(os.path.join(tele, name), "a") as f:
        f.write(json.dumps(rec) + "\n")
faults = os.environ.get("IGG_FAULT_INJECT", "")
if gen == 0 and rank == 1 and "worker_crash:step2" in faults:
    event("fault.worker_crash", step=2, status=17)
    sys.exit(17)
if gen == 1 and rank == 1 and "worker_crash:step4" in faults:
    event("fault.worker_crash", step=4, status=17)
    sys.exit(17)
event("run.complete", step=6)
sys.exit(0)
"""


def test_run_supervisor_restart_shrink_and_fault_pruning(
    clean_env, tmp_path
):
    workdir = tmp_path / "run"
    tele_dir = tmp_path / "telemetry"
    script = tmp_path / "worker.py"
    script.write_text(_FAKE_WORKER)

    def command_for(rank, nranks, rung, gen):
        return [sys.executable, str(script), str(rank)]

    rsup = sup.RunSupervisor(
        command_for,
        ladder=[2, 1],
        workdir=str(workdir),
        telemetry_dir=str(tele_dir),
        policy=sup.RecoveryPolicy(max_restarts=1, backoff_s=0.01),
        fault_spec="worker_crash:step2:proc1,worker_crash:step4:proc1,"
                   "stall:step9",
        poll_s=0.05,
        grace_s=2.0,
        name="fake",
    )
    report = rsup.run(timeout=30)
    assert report.ok, report
    actions = [i["decision"]["action"] for i in report.incidents]
    assert actions[:2] == ["restart", "shrink"]
    assert report.generations == 2
    # fired faults were pruned per relaunch; the never-fired stall remains
    assert rsup._fault_specs == ["stall:step9"]
    # the fence file tracks the final generation
    assert gen_mod.authoritative_generation(str(workdir)) == 2
    # detect -> classify -> recover order on the shared timeline
    events = _events(tele_dir / "events.jsonl")
    types_seq = [e["type"] for e in events]
    i_detect = types_seq.index("supervisor.detect")
    i_classify = types_seq.index("supervisor.classify")
    i_recover = types_seq.index("supervisor.recover")
    assert i_detect < i_classify < i_recover
    recovers = [e for e in events if e["type"] == "supervisor.recover"]
    assert [e["action"] for e in recovers[:2]] == ["restart", "shrink"]
    done = [e for e in events if e["type"] == "supervisor.done"]
    assert done and done[-1]["ok"] is True


def test_run_supervisor_resize_flow(clean_env, tmp_path):
    workdir = tmp_path / "run"
    tele_dir = tmp_path / "telemetry"
    plan_path = tmp_path / "resize.json"
    script = tmp_path / "worker.py"
    script.write_text(r"""
import json, os, sys
gen = int(os.environ["IGG_GENERATION"])
if gen == 0:
    if int(sys.argv[1]) == 0:
        with open(sys.argv[2], "w") as f:
            json.dump({"nproc": 1, "capacity": 2, "rung": 0,
                       "reason": "down"}, f)
    sys.exit(19)
sys.exit(0)
""")

    def command_for(rank, nranks, rung, gen):
        return [sys.executable, str(script), str(rank), str(plan_path)]

    seen_plans = []

    def on_resize(plan):
        seen_plans.append(plan)
        return 1  # the 1-process rung

    rsup = sup.RunSupervisor(
        command_for,
        ladder=[2, 1],
        workdir=str(workdir),
        telemetry_dir=str(tele_dir),
        policy=sup.RecoveryPolicy(max_restarts=0, backoff_s=0.01),
        on_resize=on_resize,
        resize_plan_path=str(plan_path),
        poll_s=0.05,
        grace_s=2.0,
        name="resize",
    )
    report = rsup.run(timeout=30)
    assert report.ok, report
    assert [i["kind"] for i in report.incidents] == ["resize", "healthy"]
    assert seen_plans and seen_plans[0]["reason"] == "down"
    assert not os.path.exists(plan_path)  # consumed


def test_run_supervisor_gives_up_without_a_smaller_rung(clean_env, tmp_path):
    script = tmp_path / "worker.py"
    script.write_text("import sys; sys.exit(3)\n")

    rsup = sup.RunSupervisor(
        lambda rank, nranks, rung, gen: [sys.executable, str(script)],
        ladder=[1],
        workdir=str(tmp_path / "run"),
        telemetry_dir=str(tmp_path / "telemetry"),
        policy=sup.RecoveryPolicy(max_restarts=1, backoff_s=0.01),
        poll_s=0.05,
        name="doomed",
    )
    report = rsup.run(timeout=30)
    assert not report.ok
    assert [i["decision"]["action"] for i in report.incidents] == [
        "restart", "give_up"
    ]
    assert "no smaller rung" in report.reason


def test_run_supervisor_give_up_reports_its_quarantine(clean_env, tmp_path):
    """A run that ENDS on a quarantine verdict must still name the bad
    ranks in the report (the caller's exclude-this-host signal)."""
    tele_dir = tmp_path / "telemetry"
    script = tmp_path / "worker.py"
    # every incarnation: rank 0 leaves a gather_tripwire bundle and dies
    script.write_text(r"""
import json, os, sys, time
tele = os.environ["IGG_TELEMETRY_DIR"]
os.makedirs(tele, exist_ok=True)
with open(os.path.join(tele, "flight_0.json"), "a") as f:
    f.write(json.dumps({"ts": time.time(), "rank": 0,
                        "reason": "gather_tripwire"}) + "\n")
sys.exit(1)
""")
    rsup = sup.RunSupervisor(
        lambda rank, nranks, rung, gen: [sys.executable, str(script)],
        ladder=[1],  # no smaller rung: quarantine must land as give_up
        workdir=str(tmp_path / "run"),
        telemetry_dir=str(tele_dir),
        policy=sup.RecoveryPolicy(max_restarts=2, backoff_s=0.01,
                                  quarantine_after=2),
        poll_s=0.05,
        name="quarantine",
    )
    report = rsup.run(timeout=30)
    assert not report.ok
    # strike 1 -> restart in place; strike 2 -> quarantine verdict, which
    # becomes give_up at the bottom of a one-rung ladder — still carrying
    # the quarantined rank into the report
    assert report.quarantined == (0,)
    assert [i["kind"] for i in report.incidents] == ["gather_tripwire"] * 2
    assert [i["decision"]["action"] for i in report.incidents] == [
        "restart", "give_up"
    ]
