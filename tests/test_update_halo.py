"""Tests for update_halo — the core halo-exchange engine.

Strategy (SURVEY.md §4): a numpy simulator mirrors the reference's exchange
semantics exactly (one plane per side, pack-all-then-unpack per dimension,
dimensions strictly sequential, shape-aware overlap, PROC_NULL edges keep
their values — `/root/reference/src/update_halo.jl:40-78,544-563`) and every
configuration is checked against it with coordinate-encoded unique values.
Plus: the reference's periodic full-restoration oracle
(`test_update_halo.jl:746-790`), error paths (`:61-78`), the dtype matrix
(`:109-177`), and compiled-HLO collective counts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


# ---------------------------------------------------------------- simulator


def blocks_of(arr, dims, lshape):
    """Split a global-block array into a dict {(cx,cy,cz): local block}."""
    nd = arr.ndim
    out = {}
    D = list(dims[:nd]) + [1] * (3 - nd)
    for cx in range(D[0]):
        for cy in range(D[1]):
            for cz in range(D[2]):
                ix = tuple(
                    slice(c * s, (c + 1) * s)
                    for c, s in zip((cx, cy, cz)[:nd], lshape[:nd])
                )
                out[(cx, cy, cz)] = np.array(arr[ix])
    return out


def unblocks(blocks, dims, lshape, nd, dtype):
    D = list(dims[:nd]) + [1] * (3 - nd)
    g = np.zeros(tuple(dims[d] * lshape[d] for d in range(nd)), dtype)
    for (cx, cy, cz), b in blocks.items():
        ix = tuple(
            slice(c * s, (c + 1) * s) for c, s in zip((cx, cy, cz)[:nd], lshape[:nd])
        )
        g[ix] = b
    return g


def simulate_update_halo(global_np, gg, width=1):
    """Numpy re-implementation of the reference exchange for one field
    (``width`` planes per side; width=1 is the reference's exchange).
    Partners sit at Cartesian distance ``gg.disp`` — ``MPI_Cart_shift(d,
    disp)`` semantics, independently re-derived from
    `/root/reference/src/init_global_grid.jl:89-92`."""
    nd = global_np.ndim
    w = width
    dsp = int(gg.disp)
    lshape = tuple(s // gg.dims[d] for d, s in enumerate(global_np.shape))
    blocks = blocks_of(global_np, gg.dims, lshape)

    def partner(c, d, D, per, offset):
        ci = list(c)
        ci[d] = c[d] + offset
        if per:
            ci[d] %= D
        elif not (0 <= ci[d] < D):
            return None
        return tuple(ci)

    for d in range(3):
        if d >= nd:
            continue
        o = gg.overlaps[d] + (lshape[d] - gg.nxyz[d])
        if o < 2:
            continue
        n = lshape[d]
        D = gg.dims[d]
        per = bool(gg.periods[d])
        if D == 1 and not per:
            continue
        # pack all sends from the pre-exchange state of this dim
        sends = {}
        for c, b in blocks.items():
            sl_lo = [slice(None)] * nd
            sl_hi = [slice(None)] * nd
            sl_lo[d] = slice(o - w, o)
            sl_hi[d] = slice(n - o, n - o + w)
            sends[c] = (b[tuple(sl_lo)].copy(), b[tuple(sl_hi)].copy())
        # unpack
        for c, b in blocks.items():
            # receive into hi slab [n-w, n) from the upper partner's lo send
            ci = partner(c, d, D, per, dsp)
            if ci is not None:
                sl = [slice(None)] * nd
                sl[d] = slice(n - w, n)
                b[tuple(sl)] = sends[ci][0]
            # receive into lo slab [0, w) from the lower partner's hi send
            ci = partner(c, d, D, per, -dsp)
            if ci is not None:
                sl = [slice(None)] * nd
                sl[d] = slice(0, w)
                b[tuple(sl)] = sends[ci][1]
    return unblocks(blocks, gg.dims, lshape, nd, global_np.dtype)


def unique_field(lshape, gg, dtype=np.float64):
    """Globally unique values per element (the coordinate-encoding oracle)."""
    nd = len(lshape)
    gshape = tuple(gg.dims[d] * lshape[d] for d in range(nd))
    n = int(np.prod(gshape))
    vals = (np.arange(n, dtype=np.float64) + 1.0).reshape(gshape)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return vals.astype(dtype)
    return vals.astype(dtype)


def put(arr_np):
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    spec = P(*igg.AXIS_NAMES[: arr_np.ndim])
    # device_put straight from host memory: an intermediate committed
    # jax.Array (jnp.asarray) can route device_put through jax's
    # different-device-order reshard path, which trips an internal assert
    # under the loaded full-suite run (observed as an order-dependent flake).
    return jax.device_put(np.asarray(arr_np), NamedSharding(gg.mesh, spec))


def check(config, fields_lshapes, dtype=np.float64, width=1, **initkw):
    nx, ny, nz = config
    igg.init_global_grid(nx, ny, nz, quiet=True, **initkw)
    gg = igg.get_global_grid()
    fields = [unique_field(ls, gg, dtype) for ls in fields_lshapes]
    # Low-precision dtypes can't hold unique large integers: recode small.
    if np.dtype(dtype) in (np.dtype(np.float16), np.dtype("bfloat16") if hasattr(np, "bfloat16") else np.dtype(np.float16)):
        fields = [np.mod(f, 512).astype(dtype) for f in fields]
    outs = igg.update_halo(*[put(f) for f in fields], width=width)
    if len(fields) == 1:
        outs = (outs,)
    for f, o in zip(fields, outs):
        exp = simulate_update_halo(f, gg, width)
        np.testing.assert_array_equal(np.asarray(o).astype(np.float64), exp.astype(np.float64))
    igg.finalize_global_grid()


# ---------------------------------------------------------------- oracle tests


def test_3d_nonperiodic():
    check((6, 6, 6), [(6, 6, 6)])


def coord_encoded_field(lshape, gg):
    """Fill from global coordinates (periodic-consistent: wrapped duplicate
    cells hold equal values) — the reference's oracle fill pattern
    (`test_update_halo.jl:746`: z_g*1e2 + y_g*1e1 + x_g)."""
    nd = len(lshape)
    D = gg.dims
    g = np.zeros(tuple(D[d] * lshape[d] for d in range(nd)))
    radix = 1.0
    coord_fn = [igg.x_g, igg.y_g, igg.z_g]
    for c in np.ndindex(*D[:nd]):
        coords3 = tuple(c) + (0,) * (3 - nd)
        vecs = []
        for d in range(nd):
            A = np.zeros(lshape)
            vecs.append(
                np.asarray(
                    [coord_fn[d](i, 1.0, A, coords=coords3) for i in range(lshape[d])]
                )
            )
        val = np.zeros(lshape)
        mult = 1.0
        for d in range(nd):
            shape1 = [1] * nd
            shape1[d] = lshape[d]
            val = val + vecs[d].reshape(shape1) * mult
            mult *= 1000.0
        ix = tuple(slice(c[d] * lshape[d], (c[d] + 1) * lshape[d]) for d in range(nd))
        g[ix] = val
    return g


def test_3d_all_periodic_full_restore():
    # the reference's headline oracle (test_update_halo.jl:746-790): fill from
    # global coordinates, zero the boundary planes, update_halo → fully restored
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    ref = coord_encoded_field((6, 6, 6), gg)
    zeroed = ref.copy()
    D = gg.dims
    for (cx, cy, cz) in np.ndindex(*D):
        blk = np.s_[cx * 6:(cx + 1) * 6, cy * 6:(cy + 1) * 6, cz * 6:(cz + 1) * 6]
        b = zeroed[blk]
        b[0], b[-1], b[:, 0], b[:, -1], b[:, :, 0], b[:, :, -1] = 0, 0, 0, 0, 0, 0
    out = np.asarray(igg.update_halo(put(zeroed)))
    np.testing.assert_array_equal(out, simulate_update_halo(zeroed, gg))
    np.testing.assert_array_equal(out, ref)  # full restoration


def test_3d_mixed_periods():
    check((6, 5, 7), [(6, 5, 7)], periodz=1)
    check((6, 5, 7), [(6, 5, 7)], periodx=1)


def test_staggered_fields():
    # Vx(nx+1), Vy(ny+1), Vz(nz+1) + P — reference test_update_halo.jl:828-937
    check((5, 5, 5), [(5, 5, 5), (6, 5, 5), (5, 6, 5), (5, 5, 6)])


def test_staggered_periodic():
    check((5, 5, 5), [(6, 5, 5), (5, 5, 5)], periodz=1)


def test_custom_overlaps():
    check((8, 8, 8), [(8, 8, 8)], overlapx=3, overlapy=4, overlapz=2)


def test_overlap3_periodic():
    check((8, 8, 8), [(8, 8, 8)], overlapx=3, periodx=1)


def test_disp2_nonperiodic():
    """Distance-2 partners (`MPI_Cart_shift(d, 2)` semantics): the exchange
    must talk to exactly the blocks in `GlobalGrid.neighbors` — the round-2
    parity bug had the neighbors table honoring ``disp`` while the exchange
    hard-coded shift +-1.  dims=(4,2,1): x has distance-2 partners, y's
    shifts all fall off the grid (every partner PROC_NULL), z has no
    neighbors at all."""
    check((6, 6, 6), [(6, 6, 6)], disp=2, dimx=4, dimy=2, dimz=1)


def test_disp2_periodic_wrap():
    # Periodic distance-2 partners: (c +- 2) mod 4 in x; in y the wrap
    # (c +- 2) mod 2 == c makes every block its own partner (the reference's
    # self-neighbor path, reached via Cart_shift wrap instead of dims==1).
    check((6, 6, 6), [(6, 6, 6)], disp=2, dimx=4, dimy=2, dimz=1,
          periodx=1, periody=1)


def test_disp_negative():
    # Cart_shift with a negative displacement swaps the partner directions;
    # the neighbors table and the exchange must agree there too.
    check((6, 6, 6), [(6, 6, 6)], disp=-1, dimx=4, dimy=2, dimz=1)


def test_disp2_staggered_and_width():
    # disp composes with shape-aware staggered ol and deep-halo slabs.
    check((8, 8, 8), [(8, 8, 8), (9, 8, 8)], disp=2, dimx=4, dimy=2, dimz=1,
          width=2, overlapx=4, overlapy=4, overlapz=4)


def test_disp2_all_proc_null_dim_needs_no_deep_halo():
    # dims=(4,2,1) with disp=2: every y-shift falls off the grid (all
    # partners PROC_NULL), so a width-2 slab exchange must skip y silently —
    # the deep-halo requirement applies only to dimensions that exchange.
    check((8, 8, 8), [(8, 8, 8)], disp=2, dimx=4, dimy=2, dimz=1,
          width=2, overlapx=4)  # overlapy stays at the shallow default


# disp != 1 through hide_communication is equivalence-tested against the
# plain path in tests/test_stencil_overlap.py::test_hide_communication_disp
# (the round-4 rejection was lifted: `_exchange_from_slabs` now reuses
# `_permute_slabs`' distance-disp pairs).


def test_update_halo_donate_control(monkeypatch):
    """VERDICT r4 weak #2: the public exchange exposes donation control —
    ``donate=False`` keeps the caller's buffers alive (the measured-fast
    path on runtimes where donation is slow), ``IGG_DONATE`` sets the
    default, the kwarg wins."""
    from implicitglobalgrid_tpu.ops.halo import _default_donate

    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    A = put(unique_field((6, 6, 6), gg))
    out1 = igg.update_halo(A, donate=False)
    out2 = igg.update_halo(A, donate=False)  # A still usable: not donated
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    # the donating and non-donating programs compute the same exchange
    out3 = igg.update_halo(A + 0, donate=True)
    np.testing.assert_array_equal(np.asarray(out3), np.asarray(out1))

    monkeypatch.setenv("IGG_DONATE", "0")
    assert _default_donate() is False
    out4 = igg.update_halo(A)  # env default: non-donating; A stays usable
    np.testing.assert_array_equal(np.asarray(out4), np.asarray(out1))
    monkeypatch.setenv("IGG_DONATE", "1")
    assert _default_donate() is True
    monkeypatch.delenv("IGG_DONATE")
    assert _default_donate() is True
    igg.finalize_global_grid()


def test_slab_width2():
    # Deep-halo slab exchange (width=2 on overlap-4 grids): the temporal-
    # blocking transport (one collective per k fused steps).
    check((8, 8, 8), [(8, 8, 8)], width=2, overlapx=4, overlapy=4, overlapz=4)
    check((8, 8, 8), [(8, 8, 8)], width=2, overlapx=4, overlapy=4, overlapz=4,
          periodx=1, periodz=1)


def test_slab_width2_self_neighbor():
    # width-2 local slab copy on a periodic single-block dimension
    check((8, 8, 8), [(8, 8, 8)], width=2, overlapx=4, overlapy=4, overlapz=4,
          dimy=1, periody=1, dimx=4, dimz=2)


def test_slab_width3_mixed_overlaps():
    # width-3 slabs; a dimension without halo activity may stay shallow
    check((12, 12, 8), [(12, 12, 8)], width=3, overlapx=6, overlapy=6,
          overlapz=6, periody=1)


def test_slab_width2_staggered():
    # Staggered fields slab-exchange with shape-aware ol (ol = overlap + 1
    # for the +1-sized axis), all in one call.
    check(
        (8, 8, 8),
        [(8, 8, 8), (9, 8, 8), (8, 9, 8)],
        width=2,
        overlapx=4,
        overlapy=4,
        overlapz=4,
    )


def test_slab_width_needs_deep_overlap():
    igg.init_global_grid(8, 8, 8, quiet=True)  # default overlap 2
    A = put(unique_field((8, 8, 8), igg.get_global_grid()))
    with pytest.raises(ValueError, match="overlap >= 4"):
        igg.update_halo(A, width=2)
    with pytest.raises(ValueError, match="width must be >= 1"):
        igg.update_halo(A, width=0)
    igg.finalize_global_grid()


def test_2d():
    check((6, 6, 1), [(6, 6)])
    check((6, 6, 1), [(6, 6)], periody=1)


def test_1d():
    check((6, 1, 1), [(6,)])
    check((6, 1, 1), [(6,)], periodx=1)


def test_2d_field_in_3d_grid():
    # a 2-D field in a 3-D grid has no z halo (ol(3,A)<2) and must skip dim z
    check((6, 6, 6), [(6, 6, 6), (6, 6)])


def test_self_neighbor_periodic_dim():
    # dims forced so y has a single block but periodic → local-copy fast path
    check((6, 6, 6), [(6, 6, 6)], dimy=1, periody=1, dimx=4, dimz=2)


def test_multi_field_mixed_dtypes():
    igg.init_global_grid(6, 6, 6, quiet=True)
    gg = igg.get_global_grid()
    a = unique_field((6, 6, 6), gg, np.float32)
    b = unique_field((6, 6, 6), gg, np.float64)
    oa, ob = igg.update_halo(put(a), put(b))
    np.testing.assert_array_equal(np.asarray(oa), simulate_update_halo(a, gg))
    np.testing.assert_array_equal(np.asarray(ob), simulate_update_halo(b, gg))


@pytest.mark.parametrize(
    "dtype",
    ["float16", "bfloat16", "float32", "float64", "int16", "int32",
     "complex64", "complex128"],
)
def test_dtypes(dtype):
    # reference dtype matrix: test_update_halo.jl:109-177,938-952 (ComplexF64
    # included there; x64 is enabled in this suite so complex128 is exact)
    if dtype in ("complex64", "complex128"):
        igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
        gg = igg.get_global_grid()
        re = unique_field((6, 6, 6), gg, np.float64 if dtype == "complex128" else np.float32)
        f = (re + 1j * (re + 0.5)).astype(dtype)
        out = np.asarray(igg.update_halo(put(f)))
        np.testing.assert_array_equal(out, simulate_update_halo(f, gg))
        igg.finalize_global_grid()
    else:
        check((6, 6, 6), [(6, 6, 6)], dtype=np.dtype(dtype) if dtype != "bfloat16" else jnp.bfloat16, periodx=1)


def test_float64_deep_halo_slab():
    # f64 width-2 slab exchange (the deep-halo path crossed with the x64
    # dtype matrix, matching the reference's Float64-heavy suite).
    check((8, 8, 8), [(8, 8, 8)], dtype=np.float64, width=2,
          overlapx=4, overlapy=4, overlapz=4, periodx=1)


def test_idempotent_when_consistent():
    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    f = unique_field((6, 6, 6), gg)
    once = igg.update_halo(put(f))
    twice = igg.update_halo(igg.update_halo(put(f)))
    np.testing.assert_array_equal(np.asarray(once), np.asarray(twice))


# ---------------------------------------------------------------- tracer path


def test_inside_stencil_matches_concrete():
    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    f = unique_field((6, 6, 6), gg)

    @igg.stencil
    def step(a):
        return igg.update_halo(a)

    out_stencil = np.asarray(step(put(f)))
    np.testing.assert_array_equal(out_stencil, simulate_update_halo(f, gg))


def test_update_halo_under_plain_jit_single_device():
    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True,
                         devices=[jax.devices()[0]])
    gg = igg.get_global_grid()
    f = unique_field((6, 6, 6), gg)
    out = np.asarray(jax.jit(lambda a: igg.update_halo(a))(jnp.asarray(f)))
    np.testing.assert_array_equal(out, simulate_update_halo(f, gg))


# ---------------------------------------------------------------- errors


def test_no_halo_error():
    igg.init_global_grid(6, 6, 6, quiet=True)
    bad = igg.zeros((2, 2, 2))  # ol = 2 + 2-6 < 2 in all dims
    with pytest.raises(ValueError, match="has no halo"):
        igg.update_halo(bad)


def test_duplicate_error():
    igg.init_global_grid(6, 6, 6, quiet=True)
    a = igg.zeros((6, 6, 6))
    with pytest.raises(ValueError, match="duplicate"):
        igg.update_halo(a, a)


def test_indivisible_shape_error():
    igg.init_global_grid(6, 6, 6, quiet=True)
    with pytest.raises(ValueError, match="not divisible"):
        igg.update_halo(np.zeros((7, 13, 6)))


# ---------------------------------------------------------------- HLO checks


# Per-path collective BUDGET (ISSUE 5): pinned counts AND per-hop payload
# bytes for the serialized per-field, coalesced, padded-face and pipelined
# (begin/finish) exchange variants, via `hlo_analysis.collective_payloads`.


def _collective_records(hlo):
    from implicitglobalgrid_tpu.utils.hlo_analysis import collective_payloads

    n = hlo.count(" collective-permute(") + hlo.count(" collective-permute-start(")
    recs = collective_payloads(hlo)
    assert len(recs) == n  # every hop carries a parseable payload
    return recs


def _compiled_stencil_hlo(body, args):
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.utils.compat import shard_map

    gg = igg.get_global_grid()
    specs = tuple(P(*igg.AXIS_NAMES[: a.ndim]) for a in args)
    mapped = shard_map(
        body, mesh=gg.mesh, in_specs=specs, out_specs=specs, check_vma=False
    )
    return jax.jit(mapped).lower(*args).compile().as_text()


def test_collective_permute_count():
    """Serialized path budget: 2 ppermutes per exchanged dim per FIELD with
    per-field collectives; 2 per exchanged (dim, dtype width group) with
    the coalesced default — same total payload bytes, pinned per hop."""
    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    from implicitglobalgrid_tpu.ops import halo as H

    exchanged = sum(1 for d in range(3) if gg.dims[d] > 1 or gg.periods[d])
    nfields = 2
    sig = tuple((((6, 6, 6)), "float64") for _ in range(nfields))
    f = unique_field((6, 6, 6), gg)
    g = unique_field((6, 6, 6), gg) * 2
    plane_bytes = 6 * 6 * 8  # width-1 f64 slab of the 6^3 local block

    recs = _collective_records(
        H._global_update_fn(gg, sig, 1, False, False)
        .lower(put(f), put(g)).compile().as_text()
    )
    assert len(recs) == 2 * exchanged * nfields
    assert {r["bytes"] for r in recs} == {plane_bytes}

    recs_c = _collective_records(
        H._global_update_fn(gg, sig, 1, False, True)
        .lower(put(f), put(g)).compile().as_text()
    )
    # one width group (both f64): one permute pair per dim, double payload
    assert len(recs_c) == 2 * exchanged
    assert {r["bytes"] for r in recs_c} == {nfields * plane_bytes}
    assert sum(r["bytes"] for r in recs_c) == sum(r["bytes"] for r in recs)
    igg.finalize_global_grid()


def test_collective_budget_padded_faces():
    """Padded-face staggered path budget: the 4-field `pad_faces`-layout
    exchange rides 2 collectives per field per dim with per-field
    collectives and ONE f32-group pair per dim coalesced — with the same
    total slab payload either way (the pack is a relayout, not a resend)."""
    from implicitglobalgrid_tpu.ops.halo import update_halo_padded_faces
    from implicitglobalgrid_tpu.ops.pallas_leapfrog import pad_faces

    igg.init_global_grid(8, 8, 8, overlapx=4, overlapy=4, overlapz=4,
                         periodz=1, quiet=True)
    gg = igg.get_global_grid()
    exchanged = sum(1 for d in range(3) if gg.dims[d] > 1 or gg.periods[d])

    args = [put(unique_field((8, 8, 8), gg).astype(np.float32))]
    for ax in range(3):
        shp = tuple(8 + (1 if d == ax else 0) for d in range(3))
        args.append(put(unique_field(shp, gg).astype(np.float32)))

    totals = {}
    for coalesce, n_per_dim in ((False, 8), (True, 2)):
        def body(C, Ax, Ay, Az, _co=coalesce):
            return update_halo_padded_faces(
                C, *pad_faces(Ax, Ay, Az), width=2, coalesce=_co
            )

        recs = _collective_records(_compiled_stencil_hlo(body, args))
        assert len(recs) == n_per_dim * exchanged, (coalesce, len(recs))
        totals[coalesce] = sum(r["bytes"] for r in recs)
    assert totals[True] == totals[False] > 0
    igg.finalize_global_grid()


@pytest.mark.parametrize("coalesce,n_per_dim", [(False, 4), (True, 2)])
def test_collective_budget_pipelined_begin_finish(coalesce, n_per_dim):
    """Pipelined early-dispatch path budget: `begin_slab_exchange` over two
    fields emits ``n_per_dim`` collectives per exchanged dim in the
    compiled program, with unchanged per-hop slab payloads."""
    from implicitglobalgrid_tpu.ops import halo as H

    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    exchanged = sum(1 for d in range(3) if gg.dims[d] > 1 or gg.periods[d])

    def body(a, b):
        pend = H.begin_slab_exchange((a, b), (0, 1, 2), width=1,
                                     coalesce=coalesce)
        return H.finish_slab_exchange((a, b), pend)

    f = unique_field((6, 6, 6), gg)
    recs = _collective_records(
        _compiled_stencil_hlo(body, (put(f), put(f * 2)))
    )
    assert len(recs) == n_per_dim * exchanged
    plane_bytes = 6 * 6 * 8
    expect = plane_bytes * (2 if coalesce else 1)
    assert {r["bytes"] for r in recs} == {expect}
    igg.finalize_global_grid()


@pytest.mark.parametrize("seed", range(8))
def test_random_config_sweep(seed):
    # Property sweep: random topology/periods/overlaps/staggering/width
    # against the numpy simulator (the reference relies on hand-enumerated
    # configs; the sweep guards the combinations nobody thought to write).
    rng = np.random.default_rng(1000 + seed)
    width = int(rng.integers(1, 4))
    o = 2 * width + int(rng.integers(0, 2))
    lshape = tuple(int(rng.integers(2 * o, 2 * o + 4)) for _ in range(3))
    periods = {f"period{ax}": int(rng.integers(0, 2)) for ax in "xyz"}
    overlaps = {f"overlap{ax}": o for ax in "xyz"}
    stag = [
        tuple(n + int(rng.integers(0, 2)) for n in lshape),
        lshape,
    ]
    check(lshape, stag, width=width, **periods, **overlaps)


@pytest.mark.parametrize(
    "initkw,width",
    [
        (dict(dimx=2, dimy=1, dimz=1, devices_n=2), 1),
        (dict(dimx=1, dimy=2, dimz=1, devices_n=2), 1),
        (dict(dimx=1, dimy=1, dimz=2, devices_n=2), 1),
        (dict(overlapx=4, overlapy=4, overlapz=4), 2),
        (dict(periodx=1, periody=1, periodz=1, overlapx=4, overlapy=4,
              overlapz=4), 2),
    ],
)
def test_padded_faces_exchange_matches_unpadded(initkw, width):
    """`update_halo_padded_faces` contract: owned results bitwise identical
    to unpad -> `update_halo` -> pad, across per-dimension splits, widths,
    and periodic wrap (the fused models' padded-layout exchange)."""
    from implicitglobalgrid_tpu.ops.halo import update_halo_padded_faces
    from implicitglobalgrid_tpu.ops.pallas_leapfrog import pad_faces, unpad_faces

    initkw = dict(initkw)
    n_dev = initkw.pop("devices_n", None)
    if n_dev:
        initkw["devices"] = jax.devices()[:n_dev]
    lshape = (8, 8, 8)
    igg.init_global_grid(*lshape, quiet=True, **initkw)
    gg = igg.get_global_grid()
    cell = unique_field(lshape, gg)
    faces = [
        unique_field(tuple(s + (1 if d == ax else 0) for d, s in enumerate(lshape)), gg)
        for ax in range(3)
    ]
    ref = igg.update_halo(*[put(f) for f in [cell, *faces]], width=width)
    ref = [np.asarray(A) for A in ref]

    padded_exchange = igg.stencil(
        lambda C, Ax, Ay, Az: (
            lambda out: (out[0], *unpad_faces(*out[1:]))
        )(update_halo_padded_faces(C, *pad_faces(Ax, Ay, Az), width=width))
    )
    got = padded_exchange(*[put(f) for f in [cell, *faces]])
    for name, g, r in zip(("cell", "fx", "fy", "fz"), got, ref):
        np.testing.assert_array_equal(np.asarray(g), r, err_msg=name)
    igg.finalize_global_grid()


@pytest.mark.parametrize(
    "dims,periods",
    [
        ((1, 2, 4), (0, 1, 1)),   # y + z active, periodic z (multi-hop)
        ((2, 1, 4), (0, 0, 1)),   # x + z active
        ((2, 2, 2), (1, 1, 0)),   # all dims active, non-periodic z (PROC_NULL)
    ],
)
def test_transposed_z_patch_communication_matches_packed(dims, periods):
    """The transposed thin-patch communication (`exchange_dims_t` with its
    axis-2 y-slab override + `z_patch_from_export_t`) against the packed
    128-lane path on x/y-ACTIVE grids — the interpret-mode kernel oracles
    can only run 2-device meshes (dims product cap), so the helper-level
    equivalence is pinned here on the full 8-device mesh, kernels excluded:
    both paths communicate the same synthetic export content, and the
    resulting patches must carry identical values plane-for-plane."""
    from implicitglobalgrid_tpu.ops.halo import (
        _pad8,
        _pad128,
        exchange_dims,
        exchange_dims_t,
        z_patch_from_export,
        z_patch_from_export_t,
    )

    w = 2
    n0, n1, n2 = 8, 8, 128
    PB = _pad8(4 * w)
    n1p = _pad128(n1)
    igg.init_global_grid(
        n0, n1, n2, dimx=dims[0], dimy=dims[1], dimz=dims[2],
        periodx=periods[0], periody=periods[1], periodz=periods[2],
        overlapx=2 * w, overlapy=2 * w, overlapz=2 * w, quiet=True,
    )
    gg = igg.get_global_grid()
    assert tuple(gg.dims) == dims

    def block_vals(coords):
        cx, cy, cz = coords
        key = jax.random.PRNGKey((cx * 7 + cy) * 11 + cz)
        return jax.random.normal(key, (n0, n1, 4 * w))

    def packed_fn(c):
        return jnp.pad(block_vals(c), ((0, 0), (0, 0), (0, 128 - 4 * w)))

    def transposed_fn(c):
        v = block_vals(c).transpose(0, 2, 1)  # (n0, 4w, n1)
        return jnp.pad(v, ((0, 0), (0, PB - 4 * w), (0, n1p - n1)))

    packed = igg.from_block_fn(packed_fn, (n0, n1, 128))
    transp = igg.from_block_fn(transposed_fn, (n0, PB, n1p))

    @igg.stencil
    def run_packed(e):
        e = exchange_dims(e, (0, 1), width=w)
        return z_patch_from_export(e, width=w)

    @igg.stencil
    def run_transposed(e):
        e = exchange_dims_t(e, width=w, shape=(n0, n1, n2))
        return z_patch_from_export_t(e, width=w)

    p_packed = np.asarray(igg.gather(run_packed(packed)))
    p_transp = np.asarray(igg.gather(run_transposed(transp)))
    igg.finalize_global_grid()

    # Compare plane-for-plane per block: packed lanes [0, 2w) == transposed
    # planes [0, 2w) transposed back.
    for cx in range(dims[0]):
        for cy in range(dims[1]):
            for cz in range(dims[2]):
                a = p_packed[
                    cx * n0:(cx + 1) * n0, cy * n1:(cy + 1) * n1,
                    cz * 128:cz * 128 + 2 * w,
                ]
                b = p_transp[
                    cx * n0:(cx + 1) * n0, cy * PB:cy * PB + 2 * w,
                    cz * n1p:cz * n1p + n1,
                ].transpose(0, 2, 1)
                np.testing.assert_array_equal(a, b, err_msg=f"block {(cx, cy, cz)}")
