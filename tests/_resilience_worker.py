"""Worker for the crash→restart-from-checkpoint test (not a pytest file).

Spawned in pairs by `tests/test_distributed.py::
test_worker_crash_restart_from_checkpoint`: 2 processes x 1 virtual CPU
device each, a real coordinator + gloo boundary between the blocks.  Three
modes driven by argv:

* ``normal`` — run NSTEPS diffusion steps with checkpointing, gather the
  final field to the root and save it (the uninterrupted reference).
* ``crash``  — same, but the parent armed ``IGG_FAULT_INJECT=
  worker_crash:step4:proc1``: process 1 hard-exits (status 17) right after
  the step-4 checkpoint completes; process 0 is reaped by the parent.
* ``resume`` — `RunGuard.start` restores the latest complete checkpoint
  (asserted to be step 4) and finishes the run; the final gather must be
  bit-identical to the ``normal`` output.

Watchdogged with `igg.watchdog` (the library generalization of the
hand-rolled `faulthandler` arming `_distributed_worker.py` used to carry):
a collective hang dumps all-thread stacks into the parent-captured log and
exits, instead of dying silently at the parent's outer timeout.
"""

import faulthandler
import os
import sys

# Pre-import watchdog: covers a stall inside the jax import itself; the
# igg.watchdog below replaces this timer once the package is importable.
# Must stay below the parent's 240 s wait (test_distributed.py finish_pair)
# so a hang dumps stacks into the parent-captured log before the kill.
faulthandler.dump_traceback_later(200, exit=True)

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
mode = sys.argv[4]
ckptdir = sys.argv[5]
out_path = sys.argv[6]

# Fresh process: stage the virtual-device count before jax import (older JAX
# has no jax_num_cpu_devices config option).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=1"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion3d
from implicitglobalgrid_tpu.utils import resilience

NX = 8
NSTEPS = 6
CKPT_EVERY = 2

# Below the parent's 240 s wait: a collective hang dumps stacks into the
# parent-shown log and exits, instead of being killed silently at 240 s.
with igg.watchdog(200, exit=True):
    igg.init_global_grid(
        NX,
        NX,
        NX,
        quiet=(pid != 0),
        init_distributed=True,
        distributed_kwargs=dict(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=nproc,
            process_id=pid,
        ),
    )
    assert igg.get_global_grid().dims == (2, 1, 1), igg.get_global_grid().dims

    if mode == "resume":
        latest = igg.latest_checkpoint(ckptdir)
        assert latest is not None and latest.endswith("step_00000004"), (
            f"expected the crash run to leave a complete step-4 checkpoint, "
            f"found {latest!r}"
        )

    state, params = diffusion3d.setup(NX, NX, NX, init_grid=False)
    step = diffusion3d.make_step(params)
    guard = resilience.RunGuard(
        checkpoint_every=CKPT_EVERY, checkpoint_dir=ckptdir, names=("T", "Cp")
    )
    state = resilience.guarded_time_loop(
        step, state, NSTEPS, guard=guard, sync_every_step=True
    )
    # crash mode never reaches this point on any process: proc 1 hard-exits
    # at step 4 and proc 0 is reaped by the parent when its next collective
    # loses the peer.
    assert mode in ("normal", "resume"), mode

    T = diffusion3d.temperature(state)
    got = igg.gather(T, root=0)
    if jax.process_index() == 0:
        assert got is not None and np.isfinite(got).all()
        np.save(out_path, got)

    igg.finalize_global_grid()
    assert not igg.grid_is_initialized()

print(f"WORKER {pid} OK", flush=True)
