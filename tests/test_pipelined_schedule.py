"""Boundary-first pipelined group schedule (ISSUE 2).

Oracles: the pipelined schedule must be BIT-identical to the serialized
cadence on the CPU mesh in every admissible config — ring+mid launches
partition the same tiles tile-for-tile, and the early-dispatch exchange
(`ops.halo.begin_slab_exchange`/`finish_slab_exchange`) moves exactly the
serialized slabs (corner strips patched in).  Inadmissible configs must
fall back to the serialized schedule (still bit-identical, warn-once under
``pipelined=True``).  Kernels run through the generic Pallas interpreter
(`utils.compat.pallas_force_interpret`).
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.ops import halo as halo_mod
from implicitglobalgrid_tpu.ops.overlap import (
    tile_split_error,
    tile_subset_count,
    tile_subset_map,
)
from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret


# --- tile-subset decomposition ---------------------------------------------


@pytest.mark.parametrize("ncx,ncy", [(3, 1), (3, 3), (4, 3), (5, 4), (8, 1)])
def test_ring_mid_partition_all_tiles(ncx, ncy):
    """Every admissible ring/mid pair partitions the flat tile set exactly,
    and the traced index map agrees with the Python-int one."""
    allt = set(range(ncx * ncy))
    for dims, ring, mid in (("0", "ring0", "mid0"), ("1", "ring1", "mid1"),
                            ("01", "ring01", "mid01")):
        if "0" in dims and ncx < 3:
            continue
        if "1" in dims and ncy < 3:
            continue
        r = [tile_subset_map(ring, ncx, ncy)(i)
             for i in range(tile_subset_count(ring, ncx, ncy))]
        m = [tile_subset_map(mid, ncx, ncy)(i)
             for i in range(tile_subset_count(mid, ncx, ncy))]
        assert len(set(r)) == len(r) and len(set(m)) == len(m)
        assert set(r) | set(m) == allt and not (set(r) & set(m))
        for t in m:  # interior tiles never touch a split-dim edge
            ix, iy = t // ncy, t % ncy
            if "0" in dims:
                assert 0 < ix < ncx - 1
            if "1" in dims:
                assert 0 < iy < ncy - 1
        traced = [int(tile_subset_map(ring, ncx, ncy)(jnp.int32(i)))
                  for i in range(len(r))]
        assert traced == r


def test_tile_split_error_conditions():
    # admissible reference config
    assert tile_split_error(
        (256, 256, 256), 4, 4, 32, 64, 8, (0, 1), ox=8, oy=8) is None
    # nothing active -> nothing to overlap
    assert "no x/y halo activity" in tile_split_error(
        (256, 256, 256), 4, 4, 32, 64, 8, (), ox=8, oy=8)
    # too few tiles along the split dim
    assert "3 x-tiles" in tile_split_error(
        (64, 256, 256), 4, 4, 32, 64, 8, (0,), ox=8, oy=8)
    # interior windows would read refreshed planes
    assert "refreshed x planes" in tile_split_error(
        (64, 256, 256), 6, 6, 8, 64, 8, (0,), ox=8, oy=8)
    assert "refreshed y planes" in tile_split_error(
        (256, 64, 256), 6, 6, 32, 8, 8, (1,), ox=8, oy=8)
    # deeper-than-bx overlap: the send/keep planes would lie outside the
    # ring tiles' owned rows -> must be rejected, not silently admitted
    assert "past the ring tiles" in tile_split_error(
        (256, 256, 256), 2, 2, 8, 64, 8, (0,), ox=12, oy=4)
    assert "past the ring tiles" in tile_split_error(
        (256, 256, 256), 2, 2, 32, 8, 8, (1,), ox=4, oy=12)


def test_pipelined_deep_overlap_falls_back_serialized():
    """A valid deeper-than-minimum overlap (overlapx=12 with fused_k=2,
    tile bx=8) puts the x send planes [10,12) outside the ring tiles'
    owned rows: the split must be inadmissible — and the cadence must
    still be bitwise-correct (serialized fallback) under every knob."""
    from implicitglobalgrid_tpu.models import diffusion3d

    def run(pipelined):
        kw = dict(devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
                  overlapx=12, overlapy=4, overlapz=4, quiet=True,
                  dtype=jnp.float32)
        state, params = diffusion3d.setup(24, 32, 128, **kw)
        err = diffusion3d.pipelined_support_error((24, 32, 128), 2, 4, 8, 16)
        assert err is not None and "past the ring tiles" in err, err
        with pallas_force_interpret():
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                step = diffusion3d.make_multi_step(
                    params, 4, donate=False, fused_k=2, fused_tile=(8, 16),
                    pipelined=pipelined,
                )
                out = np.asarray(jax.block_until_ready(step(*state))[0])
        igg.finalize_global_grid()
        return out

    np.testing.assert_array_equal(run(False), run(True))
    np.testing.assert_array_equal(run(False), run(None))


def test_pipelined_support_error_half_tile_no_crash():
    """A half-specified tile must resolve through the kernel ladder (the
    same contract as `zpatch_transposed`), not crash on `n1 // None`."""
    from implicitglobalgrid_tpu.models import diffusion3d

    igg.init_global_grid(256, 256, 256, dimx=2, dimy=2, dimz=2,
                         overlapx=8, overlapy=8, overlapz=8, quiet=True)
    full = diffusion3d.pipelined_support_error((256, 256, 256), 4, 4)
    assert diffusion3d.pipelined_support_error((256, 256, 256), 4, 4, bx=32) \
        in (full, None) or isinstance(
            diffusion3d.pipelined_support_error((256, 256, 256), 4, 4, bx=32),
            str,
        )
    # by-only likewise returns a verdict, never raises
    r = diffusion3d.pipelined_support_error((256, 256, 256), 4, 4, by=64)
    assert r is None or isinstance(r, str)
    igg.finalize_global_grid()


def test_zpatch_transposed_half_tile_matches_kernel_default():
    """ADVICE r5 low #4 regression: a ``by=None``-only call must resolve
    the default ladder like the kernel, not trust the lone parameter — the
    helper and `fused_diffusion_steps` must agree on the patch layout."""
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        default_tile,
        zpatch_transposed,
    )

    shape = (64, 64, 128)
    full = zpatch_transposed(shape, 4, 4)  # both None: ladder default
    tb = default_tile(shape, 4, 4, zpatch=True)
    assert full == (tb[1] == shape[1])
    # by=None only (bx given): same ladder resolution as the kernel
    assert zpatch_transposed(shape, 4, 4, bx=32) == full
    # bx=None only: likewise
    assert zpatch_transposed(shape, 4, 4, by=16) == full
    # fully-specified tiles still decide by the GIVEN by
    assert zpatch_transposed(shape, 4, 4, bx=8, by=shape[1]) is True
    assert zpatch_transposed(shape, 4, 4, bx=8, by=16) is False


# --- begin/finish slab exchange vs the serialized exchange ------------------


def test_begin_finish_matches_serialized_exchange():
    """`begin_slab_exchange` + `finish_slab_exchange` over (0,1,2) must be
    bitwise the serialized sequential-dim exchange, periodic and
    PROC_NULL dims alike (corner strips patched into the sends)."""
    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2, periodx=1,
                         overlapx=4, overlapy=4, overlapz=4, quiet=True)
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.random((32, 32, 32)))

    @igg.stencil
    def serial(A):
        return halo_mod.exchange_dims(A, (0, 1, 2), width=2)

    @igg.stencil
    def piped(A):
        pend = halo_mod.begin_slab_exchange([A], (0, 1, 2), width=2)
        (out,) = halo_mod.finish_slab_exchange([A], pend)
        return out

    np.testing.assert_array_equal(np.asarray(serial(A)), np.asarray(piped(A)))


def test_begin_finish_padded_faces_matches_serialized():
    """Same bit-identity on the staggered `pad_faces` layout with per-field
    logical shapes (the fused cadences' exchange geometry)."""
    from implicitglobalgrid_tpu.ops.pallas_leapfrog import pad_faces

    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2, periody=1,
                         overlapx=4, overlapy=4, overlapz=4, quiet=True)
    rng = np.random.default_rng(1)
    C = jnp.asarray(rng.random((32, 32, 32)))
    Vx = jnp.asarray(rng.random((34, 32, 32)))
    Vy = jnp.asarray(rng.random((32, 34, 32)))
    Vz = jnp.asarray(rng.random((32, 32, 34)))

    @igg.stencil
    def serial(C, Vx, Vy, Vz):
        Vxp, Vyp, Vzp = pad_faces(Vx, Vy, Vz)
        return halo_mod.update_halo_padded_faces(
            C, Vxp, Vyp, Vzp, width=2, dims=(0, 1)
        )

    @igg.stencil
    def piped(C, Vx, Vy, Vz):
        Vxp, Vyp, Vzp = pad_faces(Vx, Vy, Vz)
        fields = (C, Vxp, Vyp, Vzp)
        logicals = halo_mod._padded_logicals(*fields)
        pends = halo_mod.begin_slab_exchange(
            fields, (0, 1), width=2, logicals=logicals
        )
        return halo_mod.finish_slab_exchange(fields, pends, logicals=logicals)

    for r, g in zip(serial(C, Vx, Vy, Vz), piped(C, Vx, Vy, Vz)):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


# --- pipelined cadence oracles (bitwise vs serialized) ----------------------


def _diffusion_states(nloc, dims, periods, k, nt, pipelined, tile):
    from implicitglobalgrid_tpu.models import diffusion3d

    kw = dict(devices=jax.devices()[: dims[0] * dims[1] * dims[2]],
              dimx=dims[0], dimy=dims[1], dimz=dims[2],
              overlapx=2 * k, overlapy=2 * k, overlapz=2 * k, quiet=True,
              dtype=jnp.float32, **periods)
    state, params = diffusion3d.setup(*nloc, **kw)
    with pallas_force_interpret():
        step = diffusion3d.make_multi_step(
            params, nt, donate=False, fused_k=k, fused_tile=tile,
            pipelined=pipelined,
        )
        out = np.asarray(jax.block_until_ready(step(*state))[0])
    igg.finalize_global_grid()
    return out


@pytest.mark.parametrize(
    "dims,periods,nloc,tile",
    [
        # x-split, z-inactive: the non-zpatch ring0/mid0 split
        ((2, 1, 1), {}, (24, 32, 128), (8, 16)),
        # x-split + periodic z: the z-patch cadence under the split
        ((2, 1, 1), {"periodz": 1}, (24, 32, 128), (8, 16)),
        # y-split (ring1/mid1), z-inactive
        ((1, 2, 1), {}, (16, 48, 128), (8, 16)),
        # x periodic self-neighbor on 2 z-split devices: both the split
        # AND real z communication in one config
        ((1, 1, 2), {"periodx": 1}, (24, 32, 128), (8, 16)),
    ],
)
def test_pipelined_matches_serialized_bitwise(dims, periods, nloc, tile):
    k, nt = 2, 4
    ser = _diffusion_states(nloc, dims, periods, k, nt, False, tile)
    pip = _diffusion_states(nloc, dims, periods, k, nt, True, tile)
    auto = _diffusion_states(nloc, dims, periods, k, nt, None, tile)
    np.testing.assert_array_equal(ser, pip)
    np.testing.assert_array_equal(ser, auto)


def test_pipelined_inadmissible_falls_back_warn_once():
    """z-split-only grids have no x/y activity: pipelined=True warns once
    and runs the serialized schedule, bit-identically."""
    ser = _diffusion_states((16, 32, 128), (1, 1, 2), {}, 2, 4, False, (8, 16))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        pip = _diffusion_states((16, 32, 128), (1, 1, 2), {}, 2, 4, True, (8, 16))
    assert any("pipelined=True is not admissible" in str(x.message) for x in w)
    np.testing.assert_array_equal(ser, pip)


def test_pipelined_acoustic_matches_serialized_bitwise():
    from implicitglobalgrid_tpu.models import acoustic3d

    def run(pipelined):
        kw = dict(devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
                  overlapx=4, overlapy=4, overlapz=4, periodz=1, quiet=True,
                  dtype=jnp.float32)
        state, params = acoustic3d.setup(24, 32, 128, **kw)
        with pallas_force_interpret():
            step = acoustic3d.make_multi_step(
                params, 4, donate=False, fused_k=2, fused_tile=(8, 16),
                pipelined=pipelined,
            )
            out = [np.asarray(x) for x in jax.block_until_ready(step(*state))]
        igg.finalize_global_grid()
        return out

    for r, g in zip(run(False), run(True)):
        np.testing.assert_array_equal(r, g)


def test_pipelined_porous_ragged_matches_serialized_bitwise():
    """npt=5 -> lead 1 + chunks [2, 2]: the ragged PT schedule under the
    pipelined shape (patch/export widths stay w for every chunk)."""
    from implicitglobalgrid_tpu.models import porous_convection3d as pc

    def run(pipelined):
        kw = dict(devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
                  overlapx=4, overlapy=4, overlapz=4, periodz=1, quiet=True,
                  dtype=jnp.float32, npt=5)
        state, params = pc.setup(24, 32, 128, **kw)
        with pallas_force_interpret():
            step = pc.make_multi_step(
                params, 2, donate=False, fused_k=2, fused_tile=(8, 16),
                pipelined=pipelined,
            )
            out = [np.asarray(x) for x in jax.block_until_ready(step(*state))]
        igg.finalize_global_grid()
        return out

    for r, g in zip(run(False), run(True)):
        np.testing.assert_array_equal(r, g)


def test_pipelined_xla_fallback_cadence_matches_serialized():
    """f64 keeps the kernels out (itemsize envelope): pipelined=True then
    runs the XLA cadence with the early-dispatch exchange — bit-identical
    to the serialized XLA cadence."""
    from implicitglobalgrid_tpu.models import diffusion3d

    def run(pipelined):
        kw = dict(devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
                  overlapx=4, overlapy=4, overlapz=4, quiet=True,
                  dtype=jnp.float64)
        state, params = diffusion3d.setup(24, 32, 128, **kw)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            step = diffusion3d.make_multi_step(
                params, 4, donate=False, fused_k=2, pipelined=pipelined
            )
            out = np.asarray(jax.block_until_ready(step(*state))[0])
        igg.finalize_global_grid()
        return out

    np.testing.assert_array_equal(run(False), run(True))


def test_pipelined_rejected_on_per_step_path():
    from implicitglobalgrid_tpu.models import diffusion3d

    igg.init_global_grid(16, 32, 128, quiet=True)
    state, params = diffusion3d.setup(16, 32, 128, init_grid=False)
    with pytest.raises(ValueError, match="group cadences"):
        diffusion3d.make_multi_step(params, 4, pipelined=True)
    igg.finalize_global_grid()


# --- run_pipelined_group_schedule loop shape --------------------------------


def test_run_pipelined_group_schedule_phases():
    """boundary runs before interior within each group; the loop shaping
    (unrolled prefix + fori excess) is inherited from run_group_schedule."""
    from implicitglobalgrid_tpu.models._fused import (
        run_pipelined_group_schedule,
    )

    calls = []

    def boundary(ki, c):
        calls.append(("b", ki))
        return c * 2.0, "pend"

    def interior(ki, c, b_out, pend):
        assert pend == "pend"
        calls.append(("i", ki))
        return c + ki

    out = jax.jit(
        lambda c: run_pipelined_group_schedule(
            [2] * 3, boundary, interior, c
        )
    )(jnp.float32(0))
    assert float(out) == 6.0
    assert calls == [("b", 2), ("i", 2)] * 3

    calls.clear()
    out = jax.jit(
        lambda c: run_pipelined_group_schedule(
            [2] * 12, boundary, interior, c
        )
    )(jnp.float32(0))
    assert float(out) == 24.0
    # 8 unrolled groups + the fori body trace(s): strictly fewer than 12
    assert 9 <= len(calls) // 2 <= 10


# --- structural overlap evidence (jaxpr level) ------------------------------


def _kernel_permute_independent_pairs(pipelined):
    """Count (pallas_call, ppermute) pairs with no dependency either way in
    the traced program — the dataflow freedom the pipelined schedule exists
    to create, asserted below the compiler (toolchain-independent)."""
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    k = 2
    kw = dict(devices=jax.devices()[:4], dimx=4, dimy=1, dimz=1,
              overlapx=4, overlapy=4, overlapz=4, quiet=True,
              dtype=jnp.float32)
    state, params = diffusion3d.setup(40, 32, 128, **kw)  # ncx=5 at bx=8
    with pallas_force_interpret():
        step = diffusion3d.make_multi_step(
            params, 2 * k, donate=False, fused_k=k, fused_tile=(8, 16),
            pipelined=pipelined,
        )
        gg = igg.get_global_grid()
        mapped = shard_map(
            step.__wrapped__, mesh=gg.mesh,
            in_specs=(P("x", "y", "z"),) * 2, out_specs=(P("x", "y", "z"),) * 2,
            check_vma=False,
        )
        jaxpr = jax.make_jaxpr(mapped)(*state)
    igg.finalize_global_grid()
    (sm,) = [e for e in jaxpr.eqns if e.primitive.name == "shard_map"]
    inner = sm.params["jaxpr"]
    # The kernel-vs-fallback wrapper (`fused_with_xla_grad`) nests the whole
    # cadence under one custom_vjp eqn: unwrap to its primal jaxpr.
    while len(inner.eqns) == 1 and "custom_vjp" in inner.eqns[0].primitive.name:
        inner = inner.eqns[0].params["fun_jaxpr"].jaxpr
    producer = {}
    for e in inner.eqns:
        for ov in e.outvars:
            producer[id(ov)] = e

    def closure(eqn):
        seen, stack = set(), [eqn]
        while stack:
            for v in stack.pop().invars:
                p = producer.get(id(v))
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    stack.append(p)
        return seen

    def is_kernel(e):
        # the kernels' `jax.jit(pallas_call)` builders appear as pjit eqns
        if e.primitive.name == "pallas_call":
            return True
        if e.primitive.name == "pjit":
            sub = e.params.get("jaxpr")
            return sub is not None and any(
                se.primitive.name == "pallas_call" for se in sub.jaxpr.eqns
            )
        return False

    kernels = [e for e in inner.eqns if is_kernel(e)]
    perms = [e for e in inner.eqns if e.primitive.name == "ppermute"]
    assert kernels and perms, (len(kernels), len(perms))
    kc = {id(e): closure(e) for e in kernels}
    pairs = 0
    for p in perms:
        pc = closure(p)
        for c in kernels:
            if id(c) not in pc and id(p) not in kc[id(c)]:
                pairs += 1
    return pairs, len(kernels), len(perms)


def test_interior_kernel_independent_of_group_permutes():
    """Serialized: every kernel launch transitively orders against every
    group-boundary ppermute (the barrier the ISSUE names).  Pipelined: each
    group's interior launch and its in-flight permutes are mutually
    independent — the compiler is licensed to overlap them."""
    pairs_ser, nk_ser, np_ser = _kernel_permute_independent_pairs(False)
    assert nk_ser == 2 and np_ser >= 4  # 2 groups x (>=2 x-permutes)
    assert pairs_ser == 0, f"serialized schedule has {pairs_ser} free pairs"
    pairs_pip, nk_pip, np_pip = _kernel_permute_independent_pairs(True)
    assert nk_pip == 4  # ring + interior per group
    # each group's >= 2 x-permutes are independent of ITS interior launch
    assert pairs_pip >= 4, f"pipelined schedule has only {pairs_pip} free pairs"


# --- HLO analysis helpers ---------------------------------------------------

_SYNTH_HLO = """
HloModule m

ENTRY %main (p0: f32[4,8]) -> f32[4,8] {
  %p0 = f32[4,8] parameter(0)
  %cc1 = f32[4,8] custom-call(%p0), custom_call_target="tpu_custom_call"
  %slice = f32[1,8] slice(%cc1), slice={[0:1], [0:8]}
  %cps = (f32[1,8], f32[1,8], u32[], u32[]) collective-permute-start(%slice), source_target_pairs={{0,1},{1,0}}
  %cc2 = f32[4,8] custom-call(%p0), custom_call_target="tpu_custom_call"
  %cpd = f32[1,8] collective-permute-done(%cps)
  %dus = f32[4,8] dynamic-update-slice(%cc2, %cpd)
  ROOT %out = f32[4,8] custom-call(%dus), custom_call_target="tpu_custom_call"
}
"""


def test_collective_payloads_async_start_result_half():
    """ADVICE r5 low #3: the async-start payload comes from explicit
    operand/result tuple parsing (matching halves), not a blind //2."""
    from implicitglobalgrid_tpu.utils.hlo_analysis import collective_payloads

    (rec,) = collective_payloads(_SYNTH_HLO)
    assert rec["bytes"] == 1 * 8 * 4
    assert rec["shape"] == "f32[1,8]"
    assert "payload_fallback" not in rec
    # a start op whose tuple does NOT split into matching halves is flagged
    odd = _SYNTH_HLO.replace(
        "(f32[1,8], f32[1,8], u32[], u32[])", "(f32[1,8], f32[2,8], u32[])"
    )
    (rec2,) = collective_payloads(odd)
    assert rec2["payload_fallback"] == "raw-sum"
    assert rec2["bytes"] == (8 + 16) * 4


def test_pipelined_overlap_evidence_synthetic():
    """cc2 neither feeds nor consumes the permute -> one independent pair;
    cc1 feeds it and the root consumes it -> dependent."""
    from implicitglobalgrid_tpu.utils.hlo_analysis import (
        pipelined_overlap_evidence,
    )

    ev = pipelined_overlap_evidence(_SYNTH_HLO)
    assert ev["n_collectives"] == 1
    assert ev["n_custom_calls"] == 3
    assert ev["independent_pairs"] == 1
    assert ev["overlappable_collectives"] == 1
