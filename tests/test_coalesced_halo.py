"""Coalesced multi-field halo exchange (ISSUE 5).

Contract: for each exchanged dimension, every field's send slab packs into
one flat buffer per dtype byte width (bitcast to same-width unsigned ints —
the chunked gather's byte-exact transport) and rides ONE
`collective-permute` pair per (dimension, width group) instead of one per
field — BIT-identical to the per-field path across the full config matrix
(mixed dtypes incl. bf16/f64/complex, staggered ``n+1`` shapes,
``width>1``, ``disp != 1``, periodic self-neighbor, PROC_NULL edges), with
unchanged total payload bytes.  `IGG_COALESCE=0` / ``coalesce=False``
restores today's per-field collectives.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.ops import halo as H
from implicitglobalgrid_tpu.utils.hlo_analysis import collective_payloads

from test_update_halo import put, simulate_update_halo, unique_field


# ---------------------------------------------------------------- bit identity


def _check_ab(config, fields_lshapes, dtypes, width=1, **initkw):
    """Coalesced vs per-field `update_halo`: both bitwise equal to the numpy
    simulator (and hence to each other), across the whole field set."""
    nx, ny, nz = config
    igg.init_global_grid(nx, ny, nz, quiet=True, **initkw)
    gg = igg.get_global_grid()
    fields = []
    for ls, dt in zip(fields_lshapes, dtypes):
        f = unique_field(ls, gg, np.float64)
        if np.dtype(dt) in (np.dtype(np.float16), jnp.bfloat16.dtype):
            f = np.mod(f, 512)  # low-precision dtypes can't hold unique ints
        if np.dtype(dt).kind == "c":
            fields.append((f + 1j * (f + 0.5)).astype(dt))
        else:
            fields.append(np.asarray(f, dtype=dt))
    for coalesce in (True, False):
        outs = igg.update_halo(
            *[put(f) for f in fields], width=width, coalesce=coalesce
        )
        if len(fields) == 1:
            outs = (outs,)
        for f, o in zip(fields, outs):
            exp = simulate_update_halo(f, gg, width)
            got = np.asarray(o)
            if got.dtype == jnp.bfloat16.dtype:
                got, exp = got.astype(np.float64), exp.astype(np.float64)
            np.testing.assert_array_equal(got, exp)
    igg.finalize_global_grid()


def test_mixed_dtypes_all_width_groups():
    # u16 (bf16 + f16), u32 (f32 + i32), u64 (f64), complex64 riding the u32
    # group, complex128 riding u64 — every transport group in one call.
    _check_ab(
        (6, 6, 6),
        [(6, 6, 6)] * 6,
        ["bfloat16", "float16", "float32", "int32", "float64", "complex64"],
        periodx=1,
    )


def test_complex128_and_staggered():
    _check_ab(
        (5, 5, 5),
        [(5, 5, 5), (6, 5, 5), (5, 6, 5), (5, 5, 6)],
        ["complex128", "float64", "float64", "float64"],
    )


def test_staggered_deep_halo_width2():
    _check_ab(
        (8, 8, 8),
        [(8, 8, 8), (9, 8, 8), (8, 9, 8)],
        ["float64"] * 3,
        width=2, overlapx=4, overlapy=4, overlapz=4, periodz=1,
    )


def test_disp2_mixed_partners():
    # dims=(4,2,1) disp=2: x has distance-2 partners, y all-PROC_NULL, z no
    # neighbors — the coalesced pack must honor the same partner table.
    _check_ab(
        (6, 6, 6), [(6, 6, 6), (6, 6, 6)], ["float64", "float32"],
        disp=2, dimx=4, dimy=2, dimz=1,
    )


def test_disp2_periodic_wrap_self_partner():
    # y's wrap (c±2) mod 2 == c makes every block its own partner: the
    # self-partner fast path must stay per-field local copies (no packing).
    _check_ab(
        (6, 6, 6), [(6, 6, 6), (6, 6, 6)], ["float64", "float64"],
        disp=2, dimx=4, dimy=2, dimz=1, periodx=1, periody=1,
    )


def test_rank_mismatch_fields():
    # A 2-D field in the 3-D grid skips z; the 3-D partner still exchanges
    # it — per-dim participation is per FIELD, not per call.
    _check_ab((6, 6, 6), [(6, 6, 6), (6, 6)], ["float64", "float64"])


def test_bool_fields_coalesce():
    """bool cannot `bitcast_convert_type` — the transport converts {0,1} to
    uint8 instead (regression: two bool masks crashed the coalesced default
    while coalesce=False worked)."""
    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    gg = igg.get_global_grid()
    base = unique_field((6, 6, 6), gg)
    a = (np.mod(base, 2) == 0)
    b = (np.mod(base, 3) == 0)
    for coalesce in (True, False):
        oa, ob = igg.update_halo(put(a), put(b), coalesce=coalesce,
                                 donate=False)
        np.testing.assert_array_equal(np.asarray(oa), simulate_update_halo(a, gg))
        np.testing.assert_array_equal(np.asarray(ob), simulate_update_halo(b, gg))
    igg.finalize_global_grid()


def test_negative_zero_and_nan_payloads_survive_bytewise():
    """-0.0 and NaN payload bits must survive the packed transport exactly
    (the bitcast transport's whole point: a float path would lose them)."""
    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    gg = igg.get_global_grid()
    base = unique_field((6, 6, 6), gg, np.float32)
    a = -np.zeros_like(base)
    a[::3] = np.float32(np.nan)
    b = base.copy()
    b[1::3] = -0.0
    outs = {}
    for coalesce in (True, False):
        oa, ob = igg.update_halo(
            put(a), put(b), coalesce=coalesce, donate=False
        )
        outs[coalesce] = (np.asarray(oa), np.asarray(ob))
    for x, y in zip(outs[True], outs[False]):
        assert x.tobytes() == y.tobytes()  # bytewise, incl. NaN payloads/-0.0
    igg.finalize_global_grid()


# ------------------------------------------------------- collective structure


def _exchange_hlo(gg, fields, width=1, coalesce=True, donate=False):
    sig = tuple((H.local_shape(A, gg), str(A.dtype)) for A in fields)
    fn = H._global_update_fn(gg, sig, width, donate, coalesce)
    return fn.lower(*fields).compile().as_text()


def _n_collectives(hlo: str) -> int:
    return hlo.count(" collective-permute(") + hlo.count(
        " collective-permute-start("
    )


def test_five_field_exchange_two_permutes_per_dim_and_width_group():
    """The acceptance pin: a 5-field exchange emits <= 2 collective-permutes
    per exchanged (dim, width group) — here 3 groups (u32 x3 fields, u16,
    u64) over 3 exchanged dims = 18, vs 30 per-field — with IGG_COALESCE=0
    restoring the per-field count, and total payload bytes unchanged."""
    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    exchanged = sum(1 for d in range(3) if gg.dims[d] > 1 or gg.periods[d])
    base = unique_field((6, 6, 6), gg)
    fields = [
        put(np.asarray(base * (i + 1), dtype=dt))
        for i, dt in enumerate(
            ["float32", "float32", "float32", "bfloat16", "float64"]
        )
    ]
    hlo_c = _exchange_hlo(gg, fields, coalesce=True)
    hlo_p = _exchange_hlo(gg, fields, coalesce=False)
    n_groups, n_fields = 3, 5
    assert _n_collectives(hlo_c) == 2 * exchanged * n_groups
    assert _n_collectives(hlo_p) == 2 * exchanged * n_fields
    # unchanged total payload: the packed buffers move exactly the per-field
    # slab bytes (2-byte, 4-byte and 8-byte groups included)
    bytes_c = sum(r["bytes"] for r in collective_payloads(hlo_c))
    bytes_p = sum(r["bytes"] for r in collective_payloads(hlo_p))
    assert bytes_c == bytes_p > 0
    igg.finalize_global_grid()


def test_coalesce_env_default_and_cache_key(monkeypatch):
    """IGG_COALESCE wiring: 0 -> per-field, unset/1 -> coalesced; the kwarg
    wins; the resolved flag lands in the jit-cache key (so env flips cannot
    serve a stale program)."""
    from implicitglobalgrid_tpu.utils.config import coalesce_env

    monkeypatch.setenv("IGG_COALESCE", "0")
    assert H._default_coalesce() is False and coalesce_env() is False
    monkeypatch.setenv("IGG_COALESCE", "1")
    assert H._default_coalesce() is True and coalesce_env() is True
    monkeypatch.delenv("IGG_COALESCE")
    assert H._default_coalesce() is True and coalesce_env() is None
    monkeypatch.setenv("IGG_COALESCE", "x")
    with pytest.raises(ValueError, match="IGG_COALESCE"):
        H._default_coalesce()
    monkeypatch.delenv("IGG_COALESCE")

    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    f = unique_field((6, 6, 6), gg)
    H._clear_caches()
    monkeypatch.setenv("IGG_COALESCE", "0")
    igg.update_halo(put(f), put(f * 2), donate=False)
    assert {k[-1] for k in H._jit_cache} == {False}
    monkeypatch.delenv("IGG_COALESCE")
    igg.update_halo(put(f), put(f * 2), donate=False)
    assert {k[-1] for k in H._jit_cache} == {False, True}
    igg.finalize_global_grid()


def _traced_ppermutes(build, args):
    """Count ppermute eqns in the traced (jaxpr-level) program of ``build``
    shard_mapped over the grid's mesh — toolchain-independent, like
    test_pipelined_schedule's structural checks.  The recursive census is
    the budget lint's own (`scripts/check_collectives.py`) so the two
    counters cannot drift."""
    import importlib.util

    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.utils.compat import shard_map

    _here = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "igg_check_collectives_for_tests",
        os.path.join(os.path.dirname(_here), "scripts", "check_collectives.py"),
    )
    cc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cc)

    gg = igg.get_global_grid()
    specs = tuple(P(*igg.AXIS_NAMES[: a.ndim]) for a in args)
    mapped = shard_map(
        build, mesh=gg.mesh, in_specs=specs, out_specs=specs, check_vma=False
    )
    return cc._count_ppermutes(jax.make_jaxpr(mapped)(*args).jaxpr)


def test_begin_finish_coalesced_counts_and_bit_identity():
    """The pipelined schedule's early-dispatch exchange coalesces too: one
    permute pair per (dim, width group) at the jaxpr level, values bitwise
    the serialized per-field exchange (corner strips included)."""
    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2, periodx=1,
                         overlapx=4, overlapy=4, overlapz=4, quiet=True)
    rng = np.random.default_rng(7)
    A = jnp.asarray(rng.random((32, 32, 32)))
    B = jnp.asarray(rng.random((32, 32, 32)))

    def piped(coalesce):
        @igg.stencil
        def fn(A, B):
            pend = H.begin_slab_exchange(
                (A, B), (0, 1, 2), width=2, coalesce=coalesce
            )
            return H.finish_slab_exchange((A, B), pend)

        return fn

    def build(co):
        def f(a, b):
            pend = H.begin_slab_exchange((a, b), (0, 1, 2), width=2,
                                         coalesce=co)
            return H.finish_slab_exchange((a, b), pend)

        return f

    shapes = (jax.ShapeDtypeStruct((32, 32, 32), jnp.float64),) * 2
    assert _traced_ppermutes(build(True), shapes) == 2 * 3      # 1 pair/dim
    assert _traced_ppermutes(build(False), shapes) == 2 * 3 * 2  # per field

    @igg.stencil
    def serial(A, B):
        return H._update_halo_local((A, B), igg.get_global_grid(), 2, False)

    ref = serial(A, B)
    for coalesce in (True, False):
        got = piped(coalesce)(A, B)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    igg.finalize_global_grid()


def test_padded_faces_coalesced_matches_per_field():
    """`update_halo_padded_faces` (the staggered fused cadences' exchange
    geometry, per-field logical shapes): coalesced == per-field, bitwise."""
    from implicitglobalgrid_tpu.ops.pallas_leapfrog import pad_faces

    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2, periody=1,
                         overlapx=4, overlapy=4, overlapz=4, quiet=True)
    rng = np.random.default_rng(3)
    C = jnp.asarray(rng.random((32, 32, 32)))
    Vx = jnp.asarray(rng.random((34, 32, 32)))
    Vy = jnp.asarray(rng.random((32, 34, 32)))
    Vz = jnp.asarray(rng.random((32, 32, 34)))

    def run(coalesce):
        @igg.stencil
        def fn(C, Vx, Vy, Vz):
            return H.update_halo_padded_faces(
                C, *pad_faces(Vx, Vy, Vz), width=2, coalesce=coalesce
            )

        return [np.asarray(x) for x in fn(C, Vx, Vy, Vz)]

    for r, g in zip(run(False), run(True)):
        np.testing.assert_array_equal(r, g)
    igg.finalize_global_grid()


def test_transposed_export_coalesces_with_cell_field():
    """The diffusion transposed-layout pair (T + z export, y on array axis
    2): `exchange_dims_multi` with the `_T_AXES` map must equal the two
    separate single-field exchanges, bitwise."""
    from implicitglobalgrid_tpu.ops.halo import _T_AXES, _pad8, _pad128

    w = 2
    n0, n1, n2 = 8, 8, 128
    igg.init_global_grid(n0, n1, n2, dimx=2, dimy=2, dimz=2, periodx=1,
                         overlapx=2 * w, overlapy=2 * w, overlapz=2 * w,
                         quiet=True)
    gg = igg.get_global_grid()
    PB = _pad8(4 * w)
    n1p = _pad128(n1)

    def block_vals(c):
        cx, cy, cz = c
        key = jax.random.PRNGKey((cx * 5 + cy) * 13 + cz)
        return jax.random.normal(key, (n0, n1, 4 * w), jnp.float32)

    T = igg.from_block_fn(
        lambda c: jax.random.normal(
            jax.random.PRNGKey(c[0] * 100 + c[1] * 10 + c[2]),
            (n0, n1, n2), jnp.float32),
        (n0, n1, n2),
    )
    E = igg.from_block_fn(
        lambda c: jnp.pad(
            block_vals(c).transpose(0, 2, 1),
            ((0, 0), (0, PB - 4 * w), (0, n1p - n1)),
        ),
        (n0, PB, n1p),
    )

    @igg.stencil
    def separate(T, E):
        T = H.exchange_dims(T, (0, 1), width=w)
        E = H.exchange_dims_t(E, width=w, shape=(n0, n1, n2), coalesce=False)
        return T, E

    @igg.stencil
    def combined(T, E):
        return H.exchange_dims_multi(
            (T, E), (0, 1), width=w,
            logicals=(None, (n0, n1, n2)), axes=(None, _T_AXES),
            coalesce=True,
        )

    for r, g in zip(separate(T, E), combined(T, E)):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    igg.finalize_global_grid()


def test_z_patches_from_exports_coalesced_matches_per_field():
    """The staggered z-slab family's packed-export communication: coalesced
    x/y hops AND the packed one-pair z hop must reproduce the per-field
    path's patches exactly (all four lane bands)."""
    w = 2
    n0, n1 = 8, 8
    igg.init_global_grid(n0, n1, 128, dimx=2, dimy=2, dimz=2, periodz=1,
                         overlapx=2 * w, overlapy=2 * w, overlapz=2 * w,
                         quiet=True)

    def mk(shape, salt):
        def f(c):
            key = jax.random.PRNGKey(salt)
            for comp in c:
                key = jax.random.fold_in(key, comp)
            return jax.random.normal(key, shape, jnp.float32)

        return igg.from_block_fn(f, shape)

    exp_cz = mk((n0, n1, 128), 1)
    exp_x = mk((n0 + 1, n1, 128), 2)
    exp_y = mk((n0, n1 + 1, 128), 3)

    def run(coalesce):
        @igg.stencil
        def fn(a, b, c):
            return H.z_patches_from_exports(
                (a, b, c), (n0, n1, 128), width=w, coalesce=coalesce
            )

        return [np.asarray(x) for x in fn(exp_cz, exp_x, exp_y)]

    ref, got = run(False), run(True)
    for name, r, g in zip(("cz", "x", "y"), ref, got):
        # the pad128 junk tail beyond the patch bands is layout junk either
        # way; compare the real lane bands only
        np.testing.assert_array_equal(
            r[:, :, : 2 * w], g[:, :, : 2 * w], err_msg=name
        )
        if name == "cz":
            Z = H.Z_CZ_BAND
            np.testing.assert_array_equal(
                r[:, :, Z : Z + 2 * w], g[:, :, Z : Z + 2 * w]
            )
    igg.finalize_global_grid()


def test_grad_through_coalesced_exchange_matches_per_field():
    """`jax.grad` through a coalesced multi-field exchange must equal the
    per-field path's gradient EXACTLY (regression: the bitcast transport
    has no tangent, so without `_packed_transport`'s custom VJP every
    cotangent crossing a block boundary was silently dropped)."""
    igg.init_global_grid(8, 8, 8, periodx=1, quiet=True)
    gg = igg.get_global_grid()
    a = jnp.asarray(unique_field((8, 8, 8), gg))
    b = jnp.asarray(unique_field((8, 8, 8), gg) * 0.5)

    def loss(coalesce):
        ex = igg.stencil(
            lambda x, y: igg.update_halo(x, y, coalesce=coalesce)
        )

        def f(x, y):
            ox, oy = ex(x, y)
            return jnp.sum(ox**2) + jnp.sum(ox * oy)

        return f

    ga_c, gb_c = jax.grad(loss(True), argnums=(0, 1))(a, b)
    ga_p, gb_p = jax.grad(loss(False), argnums=(0, 1))(a, b)
    np.testing.assert_array_equal(np.asarray(ga_c), np.asarray(ga_p))
    np.testing.assert_array_equal(np.asarray(gb_c), np.asarray(gb_p))
    # the exchange's VJP routes cotangents ACROSS blocks: interior send
    # planes must carry non-trivial gradient, not just the local identity
    assert float(jnp.sum(jnp.abs(ga_c))) > 0
    # finite-difference spot check at a halo-plane point (cross-boundary)
    eps = 1e-6
    f = loss(True)
    for idx in [(0, 4, 4), (15, 4, 4), (7, 7, 7)]:
        fd = (f(a.at[idx].add(eps), b) - f(a.at[idx].add(-eps), b)) / (2 * eps)
        np.testing.assert_allclose(
            float(ga_c[idx]), float(fd), rtol=1e-4, atol=1e-3, err_msg=str(idx)
        )
    igg.finalize_global_grid()


def test_coalesced_telemetry_counters():
    """Trace-time counters (docs/observability.md): a coalesced trace
    records its packed collectives and per-hop payload bytes."""
    from implicitglobalgrid_tpu.utils import telemetry as tele

    igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
    gg = igg.get_global_grid()
    tele.reset()
    H._clear_caches()
    f = unique_field((6, 6, 6), gg)
    igg.update_halo(put(f), put(f * 2), donate=False, coalesce=True)
    snap = tele.snapshot()
    exchanged = sum(1 for d in range(3) if gg.dims[d] > 1 or gg.periods[d])
    assert snap["counters"]["halo.coalesced_collectives"] == 2 * exchanged
    # per (dim, group): 2 hops x (2 fields x width-1 slab plane of 6^3 f64)
    plane = {0: 36, 1: 36, 2: 36}
    expect = sum(2 * 2 * plane[d] * 8 for d in range(3))
    assert snap["counters"]["halo.coalesced_bytes"] == expect
    igg.finalize_global_grid()
