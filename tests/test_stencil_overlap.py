"""Tests for the stencil decorator and hide_communication overlap.

hide_communication must be *semantically identical* to
``update_halo(*update_fn(...))`` — verified against the plain path for
periodic/non-periodic, staggered and multi-field configurations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


def _laplacian_step(T):
    # simple 3-D stencil update, interior only (radius 1), shape-preserving
    dT = (
        T[:-2, 1:-1, 1:-1]
        + T[2:, 1:-1, 1:-1]
        + T[1:-1, :-2, 1:-1]
        + T[1:-1, 2:, 1:-1]
        + T[1:-1, 1:-1, :-2]
        + T[1:-1, 1:-1, 2:]
        - 6.0 * T[1:-1, 1:-1, 1:-1]
    )
    return T.at[1:-1, 1:-1, 1:-1].add(0.1 * dT)


def _rand_field(lshape, gg, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=tuple(gg.dims[d] * s for d, s in enumerate(lshape)))


def put(arr):
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    return jax.device_put(
        jnp.asarray(arr), NamedSharding(gg.mesh, P(*igg.AXIS_NAMES[: arr.ndim]))
    )


def test_stencil_runs_single_device_code():
    igg.init_global_grid(6, 6, 6, quiet=True)

    @igg.stencil
    def step(T):
        T = _laplacian_step(T)
        return igg.update_halo(T)

    T = igg.ones((6, 6, 6), "float64")
    out = step(T)
    assert out.shape == T.shape
    # uniform field + homogeneous laplacian → stays uniform
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_stencil_scalar_and_replicated_args():
    igg.init_global_grid(6, 6, 6, quiet=True)

    @igg.stencil
    def step(T, alpha):
        return T * alpha

    T = igg.ones((6, 6, 6), "float64")
    out = step(T, 3.0)
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_stencil_multiple_outputs():
    igg.init_global_grid(6, 6, 6, quiet=True)

    @igg.stencil
    def step(T):
        a = T + 1
        b = T[1:, :, :] * 2  # staggered-shaped output
        return a, b

    T = igg.ones((6, 6, 6), "float64")
    a, b = step(T)
    gg = igg.get_global_grid()
    assert a.shape == T.shape
    assert b.shape == (gg.dims[0] * 5, gg.dims[1] * 6, gg.dims[2] * 6)


@pytest.mark.parametrize("periods", [(0, 0, 0), (1, 1, 1), (0, 0, 1)])
def test_hide_communication_equals_plain(periods):
    igg.init_global_grid(
        8, 8, 8, periodx=periods[0], periody=periods[1], periodz=periods[2], quiet=True
    )
    f = _rand_field((8, 8, 8), igg.get_global_grid())

    plain = igg.stencil(lambda T: igg.update_halo(_laplacian_step(T)))
    overlapped = igg.stencil(igg.hide_communication(_laplacian_step, radius=1))

    out_p = np.asarray(plain(put(f)))
    out_o = np.asarray(overlapped(put(f)))
    np.testing.assert_allclose(out_o, out_p, rtol=1e-12, atol=1e-12)


def test_hide_communication_multifield_staggered():
    igg.init_global_grid(8, 8, 8, periodz=1, quiet=True)
    gg = igg.get_global_grid()

    def stepfn(P, Vx):
        # staggered acoustic-like update: Vx on (nx+1) points
        Vx = Vx.at[1:-1, :, :].add(P[1:, :, :] - P[:-1, :, :])
        P = P.at[:, :, :].add(-0.1 * (Vx[1:, :, :] - Vx[:-1, :, :]))
        return P, Vx

    P0 = _rand_field((8, 8, 8), gg, seed=1)
    Vx0 = _rand_field((9, 8, 8), gg, seed=2)

    plain = igg.stencil(lambda P, Vx: igg.update_halo(*stepfn(P, Vx)))
    overlapped = igg.stencil(igg.hide_communication(stepfn, radius=1))
    outs_p = plain(put(P0), put(Vx0))
    outs_o = overlapped(put(P0), put(Vx0))
    for a, b in zip(outs_p, outs_o):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize(
    "disp,periods",
    [(2, (0, 0, 0)), (2, (1, 1, 1)), (-1, (0, 0, 0)), (-1, (0, 0, 1))],
)
def test_hide_communication_disp(disp, periods):
    """`Cart_shift(dim, disp)` semantics through the overlapped path (VERDICT
    r4 weak #3): any disp must match the plain update_halo exchange exactly —
    `_exchange_from_slabs` shares `_permute_slabs` with it.  dims pinned to
    (4,2,1) so distance-2 shifts reach DISTINCT partners in x (on the auto
    (2,2,2) mesh disp=2 degenerates to all-PROC_NULL / self-partner and the
    distance-disp permutation would never run — the same pinning the plain
    path's disp oracles use, tests/test_update_halo.py)."""
    igg.init_global_grid(
        8, 8, 8, disp=disp, dimx=4, dimy=2, dimz=1,
        periodx=periods[0], periody=periods[1], periodz=periods[2], quiet=True,
    )
    f = _rand_field((8, 8, 8), igg.get_global_grid(), seed=3)

    plain = igg.stencil(lambda T: igg.update_halo(_laplacian_step(T)))
    overlapped = igg.stencil(igg.hide_communication(_laplacian_step, radius=1))

    out_p = np.asarray(plain(put(f)))
    out_o = np.asarray(overlapped(put(f)))
    np.testing.assert_allclose(out_o, out_p, rtol=1e-12, atol=1e-12)


def test_hide_communication_too_small_error():
    igg.init_global_grid(4, 4, 4, quiet=True, overlapx=3)
    with pytest.raises(ValueError, match="too small"):
        f = igg.ones((4, 4, 4), "float64")
        igg.stencil(igg.hide_communication(_laplacian_step))(f)


def test_fields_constructors():
    igg.init_global_grid(4, 4, 4, quiet=True)
    gg = igg.get_global_grid()
    z = igg.zeros((4, 4, 4))
    o = igg.ones((4, 4), "float32")
    f = igg.full((4,), 2.5)
    assert z.shape == tuple(d * 4 for d in gg.dims)
    assert o.shape == (gg.dims[0] * 4, gg.dims[1] * 4) and o.dtype == jnp.float32
    assert f.shape == (gg.dims[0] * 4,)
    assert float(np.asarray(f)[0]) == 2.5
    # sharding: one block per device along the mesh.  Assert on the actual
    # shard placement, not `sharding.device_set` — after shard-data fetches
    # elsewhere in the process (e.g. the benchmark harness's element-fetch
    # sync) that cached set under-counts devices on this jax version even
    # though placement and collectives remain correct (verified: 8 shards on
    # 8 distinct devices, correct update_halo results).
    assert len(z.addressable_shards) == 8
    assert len({s.device.id for s in z.addressable_shards}) == 8
    assert {tuple(s.data.shape) for s in z.addressable_shards} == {(4, 4, 4)}


def test_hide_communication_lower_rank_aux_field():
    # A 2-D parameter field on a 3-D grid must pass through hide_communication
    # windows whole (regression: IndexError in the slab/crop loops).
    import jax
    import jax.numpy as jnp

    igg.init_global_grid(8, 8, 8, quiet=True)
    T = igg.from_block_fn(
        lambda c: jnp.arange(8 * 8 * 8, dtype=jnp.float64).reshape(8, 8, 8)
        * (1.0 + c[0] + 2 * c[1] + 4 * c[2]),
        (8, 8, 8),
    )
    K2d = igg.ones((8, 8))  # no z axis

    def update(T, K2d):
        Tn = T.at[1:-1, 1:-1, 1:-1].set(
            T[1:-1, 1:-1, 1:-1] * 0.5 + K2d[1:-1, 1:-1, None] * 0.25
        )
        return Tn

    plain = igg.stencil(lambda T, K: igg.update_halo(update(T, K)))(T, K2d)
    overlapped = igg.stencil(igg.hide_communication(update, radius=1))(T, K2d)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(overlapped))


# ------------------------------------------------- structural overlap evidence


def _ppermute_waits_on_full_block(hide_comm):
    """Per-ppermute flags: does the exchange transitively depend on a
    full-block-sized computed value (the interior update)?  Asserted on the
    TRACED jaxpr, below the compiler — the optimized-HLO form of this check
    (`hlo_analysis.collective_waits`) broke when JAX 0.4.37's CPU backend
    started fusing the slab computes into the interior fusion, an
    analyzer-heuristic artifact; the dataflow property itself is
    toolchain-independent (the same move `test_pipelined_schedule.py` makes
    for the pipelined group schedule)."""
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    state, params = diffusion3d.setup(16, 16, 16, hide_comm=hide_comm, quiet=True)
    step = diffusion3d.make_step(params, donate=False)
    gg = igg.get_global_grid()
    mapped = shard_map(
        step.__wrapped__, mesh=gg.mesh,
        in_specs=(P("x", "y", "z"),) * 2, out_specs=(P("x", "y", "z"),) * 2,
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(mapped)(*state)
    igg.finalize_global_grid()
    (sm,) = [e for e in jaxpr.eqns if e.primitive.name == "shard_map"]
    inner = sm.params["jaxpr"]
    producer = {}
    for e in inner.eqns:
        for ov in e.outvars:
            producer[id(ov)] = e

    def closure(eqn):
        seen, stack, out = set(), [eqn], []
        while stack:
            for v in stack.pop().invars:
                p = producer.get(id(v))
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    out.append(p)
                    stack.append(p)
        return out

    block_elems = 16 * 16 * 16

    def is_big(e):  # an eqn COMPUTING a full-local-block-sized value
        return any(
            hasattr(ov.aval, "shape")
            and int(np.prod(ov.aval.shape or (1,))) >= block_elems
            for ov in e.outvars
        )

    perms = [e for e in inner.eqns if e.primitive.name == "ppermute"]
    return [any(is_big(e) for e in closure(pm)) for pm in perms]


def test_hide_comm_collectives_do_not_wait_on_interior():
    """Structural overlap evidence (round-2 verdict directive 3).

    On TPU the scheduler splits each collective-permute into async
    -start/-done pairs and runs independent compute between them; the
    assertable invariant here is the dataflow property that LICENSES that
    overlap: in the hide_comm program no exchange ppermute may transitively
    depend on a full-block-sized computed value (the interior update) — its
    sends are sliced from the boundary slabs alone.  The plain program is
    the differential control: there every exchange consumes the full
    updated block, a structural barrier.  The reference's analogous
    mechanism is its max-priority streams
    (`/root/reference/src/update_halo.jl:424`); `scripts/verify_tpu.py`
    carries the optimized-HLO form for the real chip's program."""
    hide_waits = _ppermute_waits_on_full_block(True)
    assert len(hide_waits) >= 6, (
        f"expected >=6 exchanges (2 per dim), found {len(hide_waits)}"
    )
    assert not any(hide_waits), (
        "hide_communication traced to exchanges that wait on the interior "
        f"update: {hide_waits}"
    )

    plain_waits = _ppermute_waits_on_full_block(False)
    assert len(plain_waits) >= 6
    assert all(plain_waits), (
        "differential control broke: the plain path's exchanges should "
        f"depend on the full update ({plain_waits}) — if this fails, the "
        "analyzer is no longer measuring what it claims"
    )


def test_stencil_replicated_output_keeps_local_shape():
    """Symmetric output-spec inference (round-2 verdict directive 6): an
    output the function made replicated (psum over the mesh) must come back
    with its local shape, not dims-many concatenated copies."""
    igg.init_global_grid(8, 8, 8, quiet=True)
    gg = igg.get_global_grid()
    T = igg.ones((8, 8, 8))

    @igg.stencil
    def stats(T):
        total = jax.lax.psum(T.sum(), ("x", "y", "z"))
        profile = jax.lax.psum(T.sum(axis=(0, 1)), ("x", "y", "z"))  # (8,)
        return total, profile, T * 2.0

    total, profile, T2 = stats(T)
    n_global = int(np.prod([gg.dims[d] * 8 for d in range(3)]))
    assert np.asarray(total).shape == ()
    assert float(np.asarray(total)) == n_global
    # replicated (8,) — NOT (dims[0]*8,) concatenated copies
    assert np.asarray(profile).shape == (8,)
    np.testing.assert_allclose(np.asarray(profile), np.full(8, n_global / 8.0))
    # the varying output stays per-block sharded
    assert T2.shape == tuple(gg.dims[d] * 8 for d in range(3))
    igg.finalize_global_grid()


def test_stencil_varying_output_still_sharded():
    # Odd-shaped per-block outputs (diff-reduced) still concatenate by rank.
    import jax.numpy as jnp

    igg.init_global_grid(8, 8, 8, quiet=True)
    gg = igg.get_global_grid()
    T = igg.from_block_fn(
        lambda c: jnp.full((8, 8, 8), 1.0 + c[0]), (8, 8, 8)
    )

    @igg.stencil
    def d0(T):
        return jnp.diff(T, axis=0)  # (7, 8, 8) per block

    out = d0(T)
    assert out.shape == (gg.dims[0] * 7, gg.dims[1] * 8, gg.dims[2] * 8)
    igg.finalize_global_grid()
