"""Tier-1 full-suite run of `igg.analysis` (docs/static-analysis.md).

The acceptance bar of ISSUE 6: the REAL package passes the full analyzer
suite with an empty finding list (modulo the justified baseline), in this
process, every tier-1 run — so a rank-divergent collective, a traced env
read, a bogus alias or a lost overlap pair introduced anywhere in the
package fails CI before it can hang a 9-minute gloo soak.  The CLI's
exit-code and selection contracts (`scripts/igg_lint.py`) are pinned here
too; per-analyzer seeded fixtures live in `tests/test_static_analysis.py`.
"""

import importlib.util
import os

import pytest

from implicitglobalgrid_tpu import analysis

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)
_spec = importlib.util.spec_from_file_location(
    "igg_lint", os.path.join(_repo, "scripts", "igg_lint.py")
)
igg_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(igg_lint)


@pytest.fixture(scope="module")
def full_report():
    """ONE full-suite run shared by the module's asserts (the traced-IR
    matrix is seconds-per-entry; keep_going=False so a crashed analyzer
    fails loudly with its real traceback, not an exit code)."""
    return analysis.run(keep_going=False)


def test_full_suite_runs_clean(full_report):
    assert full_report.errors == {}
    assert full_report.findings == [], (
        "igg-lint found unbaselined issues:\n" + full_report.human()
    )
    assert full_report.exit_code() == 0


def test_full_suite_ran_every_analyzer(full_report):
    assert full_report.ran == list(analysis.available_analyzers())
    assert full_report.skipped == []


def test_baseline_has_no_stale_suppressions(full_report):
    """A baseline entry matching no finding means the tree moved on — the
    suppression must be deleted, or it will silently mute a future
    regression that happens to collide."""
    assert full_report.stale_suppressions == []


def test_every_suppression_fired_with_a_justification(full_report):
    assert full_report.suppressed, "the shipped baseline matched nothing"
    for finding, justification in full_report.suppressed:
        assert finding.analyzer in ("knob-binding", "bench-regression")
        assert len(justification) > 40
    # the triaged set is exactly: 4 documented knob-binding contracts
    # (IGG_COALESCE / IGG_TELEMETRY / IGG_VMEM_MB / IGG_TRACE_RING) +
    # the 2 historical truncated BENCH rounds (r01/r05) + the r04 porous
    # config retirement (npt10_w2 -> npt10_w6_ragged)
    by_analyzer = {}
    for finding, _ in full_report.suppressed:
        by_analyzer.setdefault(finding.analyzer, []).append(finding)
    assert len(by_analyzer["knob-binding"]) == 4
    assert sorted((f.code, f.symbol)
                  for f in by_analyzer["bench-regression"]) == [
        ("metric-vanished", "r04"),
        ("unparseable-record", "BENCH_r01.json"),
        ("unparseable-record", "BENCH_r05.json"),
    ]


def test_cli_exit_code_contract():
    """The cheap half of the CLI surface: --list enumerates the registry,
    an AST-only subset exits 0 (its findings are baselined), an unknown
    name is an argparse error.  (--all's exit code is test 1 via the
    in-process run; re-running the trace matrix through the CLI would
    double tier-1's cost for no new information.)"""
    assert igg_lint.main(["--list"]) == 0
    assert igg_lint.main(["knob-decl"]) == 0
    assert igg_lint.main(["knob-binding", "--json"]) == 0
    with pytest.raises(SystemExit):
        igg_lint.main(["no-such-analyzer"])
    with pytest.raises(SystemExit):
        igg_lint.main([])  # no names, no --all
    with pytest.raises(SystemExit):
        # the optional-REF ambiguity: a bare `--changed-only` followed by
        # an analyzer name must be refused, not silently treated as a ref
        igg_lint.main(["--changed-only", "knob-binding", "knob-decl"])
    # the literal `=` spelling is the escape hatch for a branch that
    # genuinely shares an analyzer's name: it passes the guard and fails
    # only because no such ref exists here (exit 2, not argparse exit)
    assert igg_lint.main(["--changed-only=knob-binding", "knob-decl"]) == 2


def test_cli_sarif_stdout_stays_pure_json(capsys):
    """`--sarif -` makes stdout the artifact: the human report must ride
    stderr or the SARIF log is unparseable by its consumer."""
    import json

    rc = igg_lint.main(["bench-regression", "--sarif", "-"])
    captured = capsys.readouterr()
    assert rc == 0
    log = json.loads(captured.out)  # whole stdout parses as one JSON doc
    assert log["version"] == "2.1.0"
    assert "bench-regression" in captured.err


def test_cli_changed_only_fast_mode(tmp_path):
    """--changed-only keys analyzer selection on git-status paths; with a
    doc-only change the trace-cost analyzers must be skipped."""
    report = analysis.run(
        names=None,
        changed_paths=["docs/usage.md"],
    )
    assert report.ran == ["knob-decl"]
    assert set(report.skipped) == set(analysis.available_analyzers()) - {
        "knob-decl"
    }


def test_ensure_cpu_devices_refuses_a_conflicting_prestaged_count(
        monkeypatch):
    """A pre-staged WRONG device count must fail loudly here, not later as
    a confusing mesh-size error (idempotent when the count matches)."""
    from implicitglobalgrid_tpu.analysis.core import ensure_cpu_devices

    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    with pytest.raises(RuntimeError, match="needs 8 devices"):
        ensure_cpu_devices()
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    ensure_cpu_devices()  # matching count: a no-op
    assert os.environ["XLA_FLAGS"].count(
        "--xla_force_host_platform_device_count") == 1


def test_cli_conflicting_device_count_is_a_crash_not_findings(
        monkeypatch, capsys):
    """An environment/setup failure must exit 2 (crash), never 1 — an
    exit-code-driven consumer reads 1 as 'lint findings'."""
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    assert igg_lint.main(["grad-soundness"]) == 2
    assert "needs 8 devices" in capsys.readouterr().err


def test_hlo_analysis_changes_select_the_census_consumers():
    """utils/hlo_analysis.py IS the byte census: --changed-only selection
    on a change there must re-run the gates that consume it."""
    selected = analysis.select_for_paths(
        ["implicitglobalgrid_tpu/utils/hlo_analysis.py"])
    assert {"hlo-cost", "collective-budget"} <= set(selected)


def test_knob_binding_subset_exits_nonzero_without_baseline(capsys):
    """The raw-findings contract: --no-baseline exposes the three triaged
    per-trace knob reads and the exit code says so."""
    rc = igg_lint.main(["knob-binding", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "env-read-in-trace" in out
