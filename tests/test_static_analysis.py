"""Seeded fixtures for the `igg.analysis` suite (docs/static-analysis.md).

Each analyzer is pinned BOTH ways: a deliberately-broken fixture it must
fire on (a rank-divergent collective, a knob read inside jit, a bogus
alias, a malformed perm), and a clean twin it must stay quiet on — an
analyzer that cannot tell the two apart is a broken lint, not a clean
tree.  The framework itself (fingerprints, baseline workflow, changed-only
selection, exit codes) is tested here too; the real package's full-suite
run lives in `tests/test_lint_suite.py`.
"""

import json
import os
import sys
import textwrap

import pytest

from implicitglobalgrid_tpu.analysis import core
from implicitglobalgrid_tpu.analysis.core import (
    Baseline,
    Context,
    Finding,
    Report,
    select_for_paths,
)


def _fixture_ctx(tmp_path, sources: dict) -> Context:
    """A Context whose package root is a throwaway package built from
    ``{relative path: source}`` — the AST passes scan it instead of the
    real package."""
    pkg = tmp_path / "fixture_pkg"
    for rel, src in sources.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Context(repo_root=str(tmp_path), package_root=str(pkg))


# -- framework: Finding / fingerprints ---------------------------------------


def test_finding_rejects_unknown_severity():
    with pytest.raises(ValueError, match="severity"):
        Finding(analyzer="a", code="c", severity="FATAL", message="m")


def test_fingerprint_survives_message_and_line_drift():
    a = Finding(analyzer="a", code="c", severity="ERROR", message="old",
                path="p.py", line=10, symbol="f", anchor="K")
    b = Finding(analyzer="a", code="c", severity="ERROR", message="reworded",
                path="p.py", line=99, symbol="f", anchor="K")
    c = Finding(analyzer="a", code="c", severity="ERROR", message="old",
                path="p.py", line=10, symbol="f", anchor="OTHER")
    assert a.fingerprint == b.fingerprint
    assert a.fingerprint != c.fingerprint


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(
        {"suppressions": [{"fingerprint": "abc", "justification": "  "}]}
    ))
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(path))
    path.write_text(json.dumps(
        {"suppressions": [{"fingerprint": "abc",
                           "justification": "documented contract"}]}
    ))
    base = Baseline.load(str(path))
    f = Finding(analyzer="a", code="c", severity="ERROR", message="m")
    assert base.match(f) is None
    assert "abc" in base.suppressions


def test_shipped_baseline_is_well_formed():
    base = Baseline.load(core.DEFAULT_BASELINE)
    assert base.suppressions, "the shipped baseline lost its entries"
    for entry in base.suppressions.values():
        assert len(entry["justification"]) > 40  # a reason, not a mute


def test_report_exit_codes():
    err = Finding(analyzer="a", code="c", severity="ERROR", message="m")
    warn = Finding(analyzer="a", code="c", severity="WARNING", message="m")
    assert Report().exit_code() == 0
    assert Report(findings=[warn]).exit_code() == 0
    assert Report(findings=[warn]).exit_code(strict=True) == 1
    assert Report(findings=[err]).exit_code() == 1
    assert Report(errors={"a": "boom"}).exit_code() == 2


# -- framework: runner + baseline + changed-only ------------------------------


def _register_fake_analyzer(tmp_path, monkeypatch, body: str,
                            modname: str = "igg_fake_pass"):
    """Install a one-analyzer registry whose pass is ``body`` (a module
    defining ``run(ctx)``), returning its name.  ``modname`` must be
    unique per test — `AnalyzerSpec.load` goes through the import cache."""
    mod = tmp_path / f"{modname}.py"
    mod.write_text(textwrap.dedent(body))
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.delitem(sys.modules, modname, raising=False)
    spec = core.AnalyzerSpec(
        name="fake", module=modname, func="run", title="fixture",
        paths=("implicitglobalgrid_tpu/ops/**",),
    )
    monkeypatch.setattr(core, "REGISTRY", {"fake": spec})
    return "fake"


_FAKE_PASS = """
    from implicitglobalgrid_tpu.analysis.core import Finding

    def run(ctx):
        yield Finding(analyzer="fake", code="seeded", severity="ERROR",
                      message="seeded finding", symbol="s", anchor="a")
"""


def test_run_reports_and_baselines_and_flags_stale(tmp_path, monkeypatch):
    _register_fake_analyzer(tmp_path, monkeypatch, _FAKE_PASS)
    report = core.run(baseline=None)
    assert [f.code for f in report.findings] == ["seeded"]
    assert report.exit_code() == 1

    fp = report.findings[0].fingerprint
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"suppressions": [
        {"fingerprint": fp, "justification": "seeded fixture, intentional"},
        {"fingerprint": "dead0000dead0000",
         "justification": "left over from a removed pass"},
    ]}))
    report = core.run(baseline=str(base))
    assert report.findings == []
    assert report.exit_code() == 0  # suppressed + stale do not fail
    assert [f.fingerprint for f, _ in report.suppressed] == [fp]
    assert report.stale_suppressions == ["dead0000dead0000"]
    assert "matched no finding" in report.human()


def test_run_changed_only_selects_by_declared_paths(tmp_path, monkeypatch):
    _register_fake_analyzer(tmp_path, monkeypatch, _FAKE_PASS)
    hit = core.run(baseline=None,
                   changed_paths=["implicitglobalgrid_tpu/ops/halo.py"])
    assert hit.ran == ["fake"] and len(hit.findings) == 1
    miss = core.run(baseline=None, changed_paths=["docs/usage.md"])
    assert miss.ran == [] and miss.skipped == ["fake"]
    assert miss.findings == []


def test_run_keep_going_traps_analyzer_crashes(tmp_path, monkeypatch):
    _register_fake_analyzer(
        tmp_path, monkeypatch,
        "def run(ctx):\n    raise RuntimeError('boom')\n",
        modname="igg_fake_crashing_pass",
    )
    with pytest.raises(RuntimeError, match="boom"):
        core.run(baseline=None)
    report = core.run(baseline=None, keep_going=True)
    assert "RuntimeError: boom" in report.errors["fake"]
    assert report.exit_code() == 2


def test_run_rejects_unknown_analyzer():
    with pytest.raises(ValueError, match="unknown analyzer"):
        core.run(["no-such-pass"])


def test_changed_only_selection_of_the_real_registry():
    # Framework changes select everything; subsystem paths select their
    # declared analyzers; unrelated paths select nothing.
    assert set(select_for_paths(["scripts/igg_lint.py"])) == set(core.REGISTRY)
    ops = select_for_paths(["implicitglobalgrid_tpu/ops/halo.py"])
    assert "collective-consistency" in ops and "collective-budget" in ops
    docs = select_for_paths(["docs/usage.md"])
    assert docs == ["knob-decl"]
    assert select_for_paths(["README.md"]) == []


# -- collective-consistency: rank census --------------------------------------


def _census(sequences):
    from implicitglobalgrid_tpu.analysis.ir import RankCensus

    return RankCensus(name="fixture", sequences=sequences)


def test_divergence_detector_fires_on_rank_divergent_collective():
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
    )

    op_a = ("ppermute", ("x",), ("f32[8]",))
    op_b = ("psum", ("x",), ("f32[8]",))
    # rank 1 swaps the op kind at position 1 — the deadlock class
    found = check_rank_consistency(
        _census({0: (op_a, op_b), 1: (op_a, op_a)})
    )
    assert [f.code for f in found] == ["rank-divergent-sequence"]
    assert found[0].severity == "CRITICAL"
    assert "op 1" in found[0].message


def test_divergence_detector_fires_on_sequence_length_mismatch():
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
    )

    op = ("ppermute", ("x",), ("f32[8]",))
    found = check_rank_consistency(_census({0: (op, op), 1: (op,)}))
    assert len(found) == 1
    assert "2 collective(s)" in found[0].message


def test_divergence_detector_quiet_on_identical_sequences():
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
    )

    op = ("ppermute", ("x",), ("f32[8]",))
    assert check_rank_consistency(
        _census({r: (op, op) for r in range(8)})
    ) == []
    assert check_rank_consistency(_census({})) == []


def test_census_provider_registration_feeds_the_detector():
    from implicitglobalgrid_tpu.analysis import collectives as C

    def provider(ctx):
        yield _census({0: (("psum", ("x",), ("f32[4]",)),), 1: ()})

    C.register_census_provider(provider)
    try:
        found = C.host_plan_findings(Context())
    finally:
        C.CENSUS_PROVIDERS.remove(provider)
    assert any(
        f.code == "rank-divergent-sequence" and f.symbol == "fixture"
        for f in found
    )


def test_gather_plan_census_is_clean_and_covers_the_real_plan():
    """The PR-1 flaky-gather watch item as a static invariant: the real
    `collective_plan` must be rank-independent over the census configs."""
    from implicitglobalgrid_tpu.analysis import collectives as C

    censuses = list(C.gather_plan_censuses(Context()))
    assert len(censuses) == len(C._GATHER_PLAN_CONFIGS)
    for census in censuses:
        assert C.check_rank_consistency(census) == []
        # every simulated rank present, root included
        assert len(census.sequences) >= 1


def test_fleet_plan_census_registered_and_clean():
    """ISSUE 16: the fleet tier's in-band directive schedule joins the
    same deadlock detector as the gather/tuner/supervisor plans — the
    REAL `fleet_plan` must be rank- and fence-uniform over every action."""
    from implicitglobalgrid_tpu.analysis import collectives as C
    from implicitglobalgrid_tpu.fleet.policy import FLEET_ACTIONS

    assert C.fleet_plan_censuses in C.CENSUS_PROVIDERS
    censuses = list(C.fleet_plan_censuses(Context()))
    assert len(censuses) == 2 * len(FLEET_ACTIONS)
    for census in censuses:
        assert C.check_rank_consistency(census) == [], census.name
        assert len(census.sequences) == 4


def test_fleet_plan_census_catches_rank_keyed_directive():
    """Seeded POSITIVE fixture (ISSUE 16): a fleet directive keyed on
    rank-LOCAL fence state — one zombie rank skipping the adopt-replay
    broadcast its pool-mates enter — is the `_gather_chunked` hang class
    wearing a fleet hat, and the detector must pin it CRITICAL."""
    from implicitglobalgrid_tpu.analysis import collectives as C
    from implicitglobalgrid_tpu.analysis.ir import RankCensus
    from implicitglobalgrid_tpu.fleet.policy import fleet_plan

    census = RankCensus(
        name="host/fleet_plan[broken-rank-keyed-fence]",
        sequences={
            rank: fleet_plan(rank == 0, "respawn", stale=(rank == 2))
            for rank in range(4)
        },
    )
    findings = C.check_rank_consistency(census)
    assert findings and findings[0].severity == "CRITICAL"
    assert findings[0].code == "rank-divergent-sequence"


def test_gather_collective_plan_ignores_is_root_and_covers_ragged_tail():
    import numpy as np

    from implicitglobalgrid_tpu.ops.gather import collective_plan

    dims, batch = (3, 2), 4  # 6 blocks, batch 4 -> one ragged tail of 2
    root_plan = collective_plan(dims, batch, is_root=True)
    assert root_plan == collective_plan(dims, batch, is_root=False)
    sizes = [len(sels) for _, sels in root_plan]
    assert sizes == [4, 2]
    flat = [s for _, sels in root_plan for s in sels]
    assert flat == list(range(int(np.prod(dims))))


# -- collective-consistency: AST rank-guard pass ------------------------------


_GUARDED = """
    from jax import lax

    def exchange(x, rank):
        if rank == 0:
            x = lax.psum(x, "x")
        return x
"""

_CLEAN = """
    from jax import lax

    def exchange(x, rank):
        x = lax.psum(x, "x")          # every rank, unconditionally
        if rank == 0:
            x = x * 2                 # rank-dependent HOST math is fine
        if x.ndim == 3:
            x = lax.pmax(x, "x")      # non-rank predicate is fine
        return x
"""


def test_rank_guard_pass_fires_on_guarded_collective(tmp_path):
    from implicitglobalgrid_tpu.analysis import collectives as C

    ctx = _fixture_ctx(tmp_path, {"mod.py": _GUARDED})
    found = C.ast_findings(ctx)
    assert [f.code for f in found] == ["rank-guarded-collective"]
    f = found[0]
    assert f.severity == "CRITICAL" and f.symbol == "exchange"
    assert f.anchor == "psum" and "rank" in f.message


def test_rank_guard_pass_quiet_on_unconditional_collective(tmp_path):
    from implicitglobalgrid_tpu.analysis import collectives as C

    ctx = _fixture_ctx(tmp_path, {"mod.py": _CLEAN})
    assert C.ast_findings(ctx) == []


def test_rank_guard_pass_sees_the_early_return_form(tmp_path):
    """The commonest shape of the PR-1 divergence: non-roots bail out
    BEFORE the collective, so the collective sits after the guard, not
    inside it."""
    from implicitglobalgrid_tpu.analysis import collectives as C

    src = """
        from jax import lax

        def exchange(x, rank):
            if rank != 0:
                return x
            return lax.psum(x, "x")
    """
    found = C.ast_findings(_fixture_ctx(tmp_path / "pos", {"m.py": src}))
    assert [f.code for f in found] == ["rank-guarded-collective"]
    assert "rank" in found[0].message

    # early return on a NON-rank predicate stays quiet
    quiet = """
        from jax import lax

        def exchange(x):
            if x.ndim != 3:
                return x
            return lax.psum(x, "x")
    """
    assert C.ast_findings(
        _fixture_ctx(tmp_path / "neg", {"q.py": quiet})
    ) == []


def test_rank_guard_pass_sees_ternaries_and_nested_guards(tmp_path):
    from implicitglobalgrid_tpu.analysis import collectives as C

    src = """
        from jax import lax

        def f(x, gg):
            return lax.psum(x, "x") if gg.coords[0] == 0 else x
    """
    found = C.ast_findings(_fixture_ctx(tmp_path, {"m.py": src}))
    assert [f.code for f in found] == ["rank-guarded-collective"]
    assert "coords" in found[0].message


# -- collective-consistency: traced-census structure checks -------------------


class _StubEntry:
    name = "stub"
    mesh_shape = {"x": 2}

    def __init__(self, ops):
        self._ops = ops

    def collectives(self):
        return self._ops


def _op(perm, path=(), kind="ppermute"):
    from implicitglobalgrid_tpu.analysis.ir import CollectiveOp

    return CollectiveOp(kind=kind, axes=("x",), perm=perm, payload_bytes=0,
                        shapes=("f32[4]",), path=path)


def test_perm_checks_fire_on_malformed_permutes():
    from implicitglobalgrid_tpu.analysis.collectives import _perm_findings

    dup_src = _perm_findings(_StubEntry([_op(((0, 1), (0, 0)))]))
    assert [f.code for f in dup_src] == ["malformed-permute"]
    assert "duplicate sources" in dup_src[0].message

    dup_dst = _perm_findings(_StubEntry([_op(((0, 1), (1, 1)))]))
    assert "duplicate targets" in dup_dst[0].message

    oob = _perm_findings(_StubEntry([_op(((0, 5),))]))
    assert "outside the axis size" in oob[0].message


def test_perm_checks_fire_on_collective_under_cond():
    from implicitglobalgrid_tpu.analysis.collectives import _perm_findings

    found = _perm_findings(
        _StubEntry([_op(((0, 1), (1, 0)), path=("while", "cond"))])
    )
    assert [f.code for f in found] == ["collective-under-cond"]
    assert found[0].severity == "CRITICAL"


def test_perm_checks_quiet_on_valid_partial_permutation():
    from implicitglobalgrid_tpu.analysis.collectives import _perm_findings

    # a PROC_NULL-masked edge hop: partial perm, no dup, in range
    assert _perm_findings(_StubEntry([_op(((0, 1),))])) == []


# -- knob-binding -------------------------------------------------------------


_KNOB_IN_TRACE = """
    import os
    from jax import jit

    def body(x):
        scale = int(os.environ.get("IGG_FIXTURE_SCALE", "1"))
        return x * scale

    stepper = jit(body)
"""

_KNOB_HOST_SIDE = """
    import os
    from jax import jit

    def _scale():
        return int(os.environ.get("IGG_FIXTURE_SCALE", "1"))

    def make_step():
        scale = _scale()              # resolved HOST-side, then closed over

        def body(x):
            return x * scale

        return jit(body)
"""


def test_knob_binding_fires_on_env_read_inside_jit(tmp_path):
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    found = run_knob_binding(_fixture_ctx(tmp_path, {"m.py": _KNOB_IN_TRACE}))
    assert [f.code for f in found] == ["env-read-in-trace"]
    f = found[0]
    assert f.anchor == "IGG_FIXTURE_SCALE" and f.severity == "ERROR"
    assert "TRACE time" in f.message


def test_knob_binding_quiet_when_knob_resolved_host_side(tmp_path):
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    found = run_knob_binding(
        _fixture_ctx(tmp_path, {"m.py": _KNOB_HOST_SIDE})
    )
    assert found == []


def test_knob_binding_follows_calls_and_accessor_args(tmp_path):
    """The package idiom: a traced closure calling an accessor that calls
    the generic reader — the knob name rides the constant first arg."""
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    src = """
        import os
        from jax import lax
        from .cfg import int_env

        def make(n):
            def inner(c, x):
                return c, x * int_env("IGG_FIXTURE_DEPTH")

            def body(x):
                return lax.scan(inner, 0, x)

            return body
    """
    cfg = """
        import os

        def int_env(name):
            return int(os.environ.get(name, "0"))
    """
    found = run_knob_binding(
        _fixture_ctx(tmp_path, {"m.py": src, "cfg.py": cfg})
    )
    assert [f.anchor for f in found] == ["IGG_FIXTURE_DEPTH"]


def test_real_package_knob_binding_matches_the_baseline():
    """Triage pin: every knob-binding finding on the REAL package is one of
    the four baselined per-trace contracts — a new traced env read must
    show up here (and fail tier-1 via test_lint_suite) until triaged."""
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    base = Baseline.load(core.DEFAULT_BASELINE)
    found = run_knob_binding(Context())
    unbaselined = [f for f in found if base.match(f) is None]
    assert unbaselined == [], [f.message for f in unbaselined]
    assert {f.anchor for f in found} == {
        "IGG_COALESCE", "IGG_TELEMETRY", "IGG_VMEM_MB",
        # ISSUE 10: begin/finish_slab_exchange's trace-time spans read the
        # ring capacity — same documented contract as IGG_TELEMETRY
        "IGG_TRACE_RING",
    }


# -- knob-decl ----------------------------------------------------------------


def test_knob_decl_fires_on_undeclared_and_undocumented(tmp_path):
    from implicitglobalgrid_tpu.analysis.knobs import knob_decl_findings

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text('import os\nos.environ.get("IGG_BOGUS")\n')
    config = tmp_path / "config.py"
    config.write_text('"""knob table: (none)"""\n')
    usage = tmp_path / "usage.md"
    usage.write_text("# usage\n")
    found = knob_decl_findings(str(tmp_path), str(pkg), str(config),
                               str(usage))
    assert sorted(f.code for f in found) == [
        "undeclared-knob", "undocumented-knob",
    ]
    assert all(f.symbol == "IGG_BOGUS" for f in found)

    config.write_text('"""table: IGG_BOGUS"""\n')
    usage.write_text("| `IGG_BOGUS` | fixture row |\n")
    assert knob_decl_findings(str(tmp_path), str(pkg), str(config),
                              str(usage)) == []


# -- pallas-aliasing ----------------------------------------------------------


def test_alias_pair_validation_fires_on_bogus_pairs():
    from implicitglobalgrid_tpu.analysis.aliasing import validate_alias_pairs

    a = ((8, 8), "float32")
    b = ((8, 9), "float32")
    assert validate_alias_pairs([(0, 0)], [a], [a]) == []
    assert "out of range" in validate_alias_pairs([(2, 0)], [a], [a])[0]
    assert "out of range" in validate_alias_pairs([(0, 3)], [a], [a])[0]
    probs = validate_alias_pairs([(0, 0), (1, 0)], [a, a], [a])
    assert any("two inputs" in p for p in probs)
    probs = validate_alias_pairs([(0, 0)], [b], [a])
    assert any("shape+dtype" in p for p in probs)


_BAD_ALIAS = """
    import jax.experimental.pallas as pl

    def build(kernel, shapes):
        return pl.pallas_call(
            kernel, out_shape=shapes,
            input_output_aliases={0: 0, 1: 0},
        )
"""

_GOOD_ALIAS = """
    import jax.experimental.pallas as pl

    def build(kernel, shapes):
        return pl.pallas_call(
            kernel, out_shape=shapes,
            input_output_aliases={0: 0, 1: 1},
        )
"""


def test_aliasing_ast_pass_fires_on_duplicate_output_alias(tmp_path):
    from implicitglobalgrid_tpu.analysis import aliasing

    found = aliasing.ast_findings(
        _fixture_ctx(tmp_path, {"k.py": _BAD_ALIAS})
    )
    assert [f.code for f in found] == ["bad-alias-literal"]
    assert "two inputs on one" in found[0].message


def test_aliasing_ast_pass_quiet_on_injective_alias(tmp_path):
    from implicitglobalgrid_tpu.analysis import aliasing

    assert aliasing.ast_findings(
        _fixture_ctx(tmp_path, {"k.py": _GOOD_ALIAS})
    ) == []


def test_aliasing_ast_pass_fires_on_negative_donation(tmp_path):
    from implicitglobalgrid_tpu.analysis import aliasing

    src = """
        from jax import jit

        def make(f):
            return jit(f, donate_argnums=(-1,))
    """
    found = aliasing.ast_findings(_fixture_ctx(tmp_path, {"d.py": src}))
    assert [f.code for f in found] == ["bad-donate-literal"]


# -- overlap-independence -----------------------------------------------------


def _shard_mapped_jaxpr(body, nargs=1):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from implicitglobalgrid_tpu.analysis.ir import unwrap_inner
    from implicitglobalgrid_tpu.utils.compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))
    mapped = shard_map(body, mesh=mesh, in_specs=(P("x"),) * nargs,
                       out_specs=(P("x"),) * nargs, check_vma=False)
    args = (jnp.zeros((8,), jnp.float32),) * nargs
    return unwrap_inner(jax.make_jaxpr(mapped)(*args).jaxpr)


def test_independence_pairs_counts_dataflow_freedom():
    from jax import lax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.analysis.ir import independence_pairs

    ring = [(0, 1), (1, 0)]
    is_k = lambda e: e.primitive.name == "sin"  # noqa: E731

    def dependent(x):
        return (jnp.sin(lax.ppermute(x, "x", ring)),)

    pairs, nk, nc = independence_pairs(
        _shard_mapped_jaxpr(dependent), is_kernel=is_k
    )
    assert (pairs, nk, nc) == (0, 1, 1)

    def independent(x, z):
        return jnp.sin(x), lax.ppermute(z, "x", ring)

    pairs, nk, nc = independence_pairs(
        _shard_mapped_jaxpr(independent, nargs=2), is_kernel=is_k
    )
    assert (pairs, nk, nc) == (1, 1, 1)


def test_eqn_presence_classifies_collective_envelopes():
    """A pjit/custom-vjp envelope whose body is all collectives must join
    the census as a collective (the coalesced `_packed_transport` shape);
    one containing none of either stays out."""
    import jax
    from jax import lax

    from implicitglobalgrid_tpu.analysis.ir import _eqn_presence

    ring = [(0, 1), (1, 0)]

    def body(x):
        wrapped = jax.jit(lambda v: lax.ppermute(v, "x", ring))
        return (wrapped(x) + 1.0,)

    jaxpr = _shard_mapped_jaxpr(body)
    by_name = {e.primitive.name: e for e in jaxpr.eqns}
    assert _eqn_presence(by_name["pjit"]) == (False, True)
    assert _eqn_presence(by_name["add"]) == (False, False)


# -- collective-budget --------------------------------------------------------


def _hlo_fixture(n_perm: int, *, bad_start: bool = False) -> str:
    """Synthetic optimized-HLO text with ``n_perm`` collective-permutes in
    the shape `utils.hlo_analysis.collective_payloads` parses."""
    lines = ["ENTRY %main (p0: f32[6,6]) -> f32[6,6] {",
             "  %p0 = f32[6,6]{1,0} parameter(0)"]
    for i in range(n_perm):
        lines.append(
            f"  %cp{i} = f32[6,6]{{1,0}} collective-permute(%p0), "
            f"source_target_pairs={{{{0,1}},{{1,0}}}}"
        )
    if bad_start:
        # async-start whose tuple halves do NOT match -> raw-sum fallback
        lines.append(
            "  %cps = (f32[6,6]{1,0}, f32[4,6]{1,0}, u32[]) "
            "collective-permute-start(%p0), source_target_pairs={{0,1}}"
        )
    lines += ["  ROOT %r = f32[6,6]{1,0} add(%p0, %p0)", "}"]
    return "\n".join(lines)


def test_hlo_budget_cross_check_fires_and_stays_quiet():
    from implicitglobalgrid_tpu.analysis.budget import hlo_budget_findings

    # porous budget: 1 pair x 3 dims = 6 permutes allowed
    assert hlo_budget_findings(_hlo_fixture(6)) == []

    over = hlo_budget_findings(_hlo_fixture(8))
    assert [f.code for f in over] == ["hlo-budget-exceeded"]
    assert "split the coalesced hops" in over[0].message

    empty = hlo_budget_findings(_hlo_fixture(0))
    assert "hlo-census-broken" in [f.code for f in empty]


def test_hlo_budget_cross_check_flags_unaccounted_payloads():
    from implicitglobalgrid_tpu.analysis.budget import hlo_budget_findings

    found = hlo_budget_findings(_hlo_fixture(5, bad_start=True))
    assert [f.code for f in found] == ["hlo-payload-fallback"]
    assert found[0].severity == "WARNING"


def test_batched_census_fires_on_collective_count_mismatch():
    """Seeded positive fixture (ISSUE 8): a batched-exchange census whose
    B>1 counts differ from the B=1 baseline must fail — the
    B-for-the-price-of-1 claim as a static invariant — and the matching
    census must stay quiet."""
    from implicitglobalgrid_tpu.analysis.budget import (
        batched_census_findings,
    )

    base = {"x": 2, "y": 2, "z": 2}
    # clean: identical counts at every B
    assert batched_census_findings(
        {"diffusion": {1: dict(base), 4: dict(base)}}
    ) == []

    # regression: the B=4 exchange re-serialized per member in x
    found = batched_census_findings(
        {"diffusion": {1: dict(base), 4: {"x": 8, "y": 2, "z": 2}}}
    )
    assert [f.code for f in found] == ["batched-budget-mismatch"]
    assert found[0].symbol == "diffusion/batch4"
    assert "re-serialized" in found[0].message

    # a baseline that saw no collectives is a broken census, not a pass
    assert [
        f.code
        for f in batched_census_findings(
            {"porous": {1: {"x": 0, "y": 0, "z": 0}}}
        )
    ] == ["census-broken"]


def test_batched_census_real_trace_is_b_invariant():
    """The REAL traced census: every model's coalesced exchange must emit
    identical per-dimension ppermute counts at B=1 and B=4 (tier-1 also
    runs this through the suite's `budget.run`)."""
    from implicitglobalgrid_tpu.analysis.budget import (
        BATCHED_CENSUS_B,
        batched_budget_findings,
        batched_exchange_census,
    )

    census = batched_exchange_census()
    assert set(census) == {"diffusion", "acoustic", "porous"}
    for model, variants in census.items():
        assert variants[1] == variants[BATCHED_CENSUS_B], (model, variants)
        assert sum(variants[1].values()) > 0, (model, variants)
    assert batched_budget_findings() == []


def test_entry_budget_census_fires_on_per_field_regression():
    """The suite path counts the SHARED traced entries: a coalesce=True
    entry showing per-field collective counts must fire, and a control
    entry that lost its collectives must flag the census itself."""
    from implicitglobalgrid_tpu.analysis.budget import entry_budget_findings

    from implicitglobalgrid_tpu.analysis.ir import CollectiveOp

    def entry(name, axis_counts):
        ops = []
        for axis, cnt in axis_counts.items():
            ops += [
                CollectiveOp(kind="ppermute", axes=(axis,), perm=((0, 1),),
                             payload_bytes=0, shapes=("f32[4]",), path=())
            ] * cnt
        stub = _StubEntry(ops)
        stub.name = name
        return stub

    # diffusion (1 field): coalesced entry regressed to 6 permutes in x
    found = entry_budget_findings(
        [
            entry("exchange/diffusion[coalesce=True]", {"x": 6, "y": 2, "z": 2}),
            entry("exchange/diffusion[coalesce=False]", {"x": 2}),
        ],
        budget_pairs={"diffusion": 1},
    )
    assert [f.code for f in found] == ["budget-exceeded"]
    assert found[0].symbol == "diffusion/dim0"

    # clean twin stays quiet
    assert entry_budget_findings(
        [
            entry("exchange/diffusion[coalesce=True]", {"x": 2, "y": 2, "z": 2}),
            entry("exchange/diffusion[coalesce=False]", {"x": 2}),
        ],
        budget_pairs={"diffusion": 1},
    ) == []

    # a missing entry is a broken census, not a clean run
    assert [
        f.code
        for f in entry_budget_findings([], budget_pairs={"diffusion": 1})
    ] == ["census-broken"]


def test_budget_analyzer_fires_when_budget_tightened_to_zero():
    """Liveness: with an impossible budget the census must report every
    exchanged dimension — proving it sees the real collectives (the clean
    run on the true budget is tier-1's test_collective_budget)."""
    from implicitglobalgrid_tpu.analysis.budget import budget_findings

    found = budget_findings(budget_pairs={"diffusion": 0})
    assert [f.code for f in found] == ["budget-exceeded"] * 3
    assert {f.symbol for f in found} == {
        "diffusion/dim0", "diffusion/dim1", "diffusion/dim2",
    }


# -- hlo-cost -----------------------------------------------------------------


class _CostCtx:
    """Stub Context: a traced exchange entry + an HLO text, nothing else."""

    def __init__(self, entries, hlo):
        self._entries, self._hlo = entries, hlo

    def exchange_entries(self):
        return self._entries

    def exchange_hlo(self):
        return self._hlo


def _traced_exchange_stub(payload_bytes_list):
    from implicitglobalgrid_tpu.analysis.ir import (
        EXCHANGE_HLO_PROGRAM,
        CollectiveOp,
    )

    ops = [
        CollectiveOp(kind="ppermute", axes=("x",), perm=((0, 1),),
                     payload_bytes=b, shapes=(f"f32[{b // 4}]",), path=())
        for b in payload_bytes_list
    ]
    stub = _StubEntry(ops)
    stub.name = EXCHANGE_HLO_PROGRAM
    return stub


def test_cost_text_census_counts_the_hlo_structure():
    from implicitglobalgrid_tpu.analysis.costmodel import text_census

    c = text_census(_hlo_fixture(6))
    assert c["collective_permutes"] == 6
    assert c["collective_payload_bytes"] == 6 * 144  # f32[6,6] per hop
    assert c["payload_fallbacks"] == 0
    assert c["fusions"] == 0 and c["kernel_launches"] == 0


def test_payload_crosscheck_byte_exact_and_fires_on_mismatch():
    from implicitglobalgrid_tpu.analysis.costmodel import (
        payload_crosscheck_findings,
    )

    # byte-exact twin: 6 traced hops of 144 B vs 6 compiled permutes
    clean = payload_crosscheck_findings(
        _CostCtx([_traced_exchange_stub([144] * 6)], _hlo_fixture(6))
    )
    assert clean == []

    # a widened hop (the seeded 2x payload regression) must fire
    widened = payload_crosscheck_findings(
        _CostCtx([_traced_exchange_stub([288] + [144] * 5), ],
                 _hlo_fixture(6))
    )
    assert [f.code for f in widened] == ["payload-mismatch"]

    # a lost hop fires too (count is part of the identity)
    lost = payload_crosscheck_findings(
        _CostCtx([_traced_exchange_stub([144] * 5)], _hlo_fixture(6))
    )
    assert [f.code for f in lost] == ["payload-mismatch"]

    # a raw-sum fallback is its own failure, declared by the parser
    fb = payload_crosscheck_findings(
        _CostCtx([_traced_exchange_stub([144] * 5 + [240])],
                 _hlo_fixture(5, bad_start=True))
    )
    assert "payload-fallback" in [f.code for f in fb]

    # no traced twin at all = a broken cross-check, never a clean pass
    gone = payload_crosscheck_findings(_CostCtx([], _hlo_fixture(6)))
    assert [f.code for f in gone] == ["crosscheck-broken"]


def _cost_baseline(metrics, tolerances=None):
    return {
        "version": 1,
        "tolerances": tolerances or {"flops": 0.02, "*": 0.0},
        "programs": {
            "prog": {
                "metrics": dict(metrics),
                "justifications": {m: "pinned by fixture" for m in metrics},
            }
        },
    }


def test_compare_census_fires_on_inflated_payload_and_defused_kernel():
    from implicitglobalgrid_tpu.analysis.costmodel import compare_census

    base = _cost_baseline(
        {"collective_payload_bytes": 8064, "kernel_launches": 3,
         "flops": 1000}
    )
    clean = {"prog": {"collective_payload_bytes": 8064,
                      "kernel_launches": 3, "flops": 1000}}
    assert compare_census(clean, base) == []

    # the seeded 2x payload inflation (acceptance fixture) fails the gate
    doubled = {"prog": {"collective_payload_bytes": 16128,
                        "kernel_launches": 3, "flops": 1000}}
    found = compare_census(doubled, base)
    assert [f.code for f in found] == ["cost-regression"]
    assert found[0].anchor == "collective_payload_bytes"

    # one defused extra kernel launch fails too (structural = exact band)
    defused = {"prog": {"collective_payload_bytes": 8064,
                        "kernel_launches": 4, "flops": 1000}}
    found = compare_census(defused, base)
    assert [f.code for f in found] == ["cost-regression"]
    assert found[0].anchor == "kernel_launches"


def test_compare_census_tolerance_bands_and_two_sided_drift():
    from implicitglobalgrid_tpu.analysis.costmodel import compare_census

    base = _cost_baseline({"flops": 1000, "kernel_launches": 3})
    inside = {"prog": {"flops": 1010, "kernel_launches": 3}}  # +1% < 2%
    assert compare_census(inside, base) == []
    outside = {"prog": {"flops": 1030, "kernel_launches": 3}}  # +3% > 2%
    assert [f.code for f in compare_census(outside, base)] == [
        "cost-regression"
    ]
    # an IMPROVEMENT outside the band is news, not silent drift
    better = {"prog": {"flops": 900, "kernel_launches": 3}}
    found = compare_census(better, base)
    assert [f.code for f in found] == ["cost-regression"]
    assert "improved" in found[0].message


def test_compare_census_reports_lost_and_unbaselined():
    from implicitglobalgrid_tpu.analysis.costmodel import compare_census

    base = _cost_baseline({"flops": 1000})
    # the toolchain stopped reporting a gated metric: blind spot, ERROR
    lost = compare_census({"prog": {"kernel_launches": 3}}, base)
    codes = {f.code for f in lost}
    assert "metric-lost" in codes and "metric-unbaselined" in codes
    # a program disappearing from the matrix is an ERROR as well
    assert [f.code for f in compare_census({}, base)] == ["program-missing"]
    # a new program with no baseline entry is a WARNING nudge to refresh
    extra = compare_census(
        {"prog": {"flops": 1000}, "prog2": {"flops": 5}}, base
    )
    assert [f.code for f in extra] == ["program-unbaselined"]
    assert extra[0].severity == "WARNING"


def test_cost_baseline_loader_enforces_the_audit_contract(tmp_path):
    from implicitglobalgrid_tpu.analysis import costmodel

    p = tmp_path / "cost_baseline.json"
    p.write_text(json.dumps({
        "version": 1,
        "programs": {"prog": {"metrics": {"flops": 1},
                              "justifications": {"flops": "  "}}},
    }))
    with pytest.raises(ValueError, match="unjustified"):
        costmodel.load_baseline(str(p))
    p.write_text(json.dumps({"version": 99, "programs": {}}))
    with pytest.raises(ValueError, match="version"):
        costmodel.load_baseline(str(p))


# -- grad-soundness -----------------------------------------------------------


def test_dropper_scan_fires_on_bitcast_in_tangent_path():
    """The seeded PR-5 class: a bitcast transport with NO custom VJP on the
    differentiable path must be CRITICAL (jax.grad silently zeroes every
    cotangent through it)."""
    import jax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.analysis.gradflow import dropper_findings

    def broken(x):
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
        return jax.lax.bitcast_convert_type(u, jnp.float32) * 2.0

    jaxpr = jax.make_jaxpr(broken)(jnp.ones(4, jnp.float32))
    found = dropper_findings(jaxpr.jaxpr, "fixture/broken")
    assert [f.severity for f in found] == ["CRITICAL"]
    assert found[0].code == "cotangent-dropper"
    assert "bitcast_convert_type" in found[0].message
    assert "_packed_transport" in found[0].fix_hint
    # in-repo source locations are REPO-RELATIVE: the fingerprint hashes
    # the path, so an absolute checkout prefix would pin baselines (and
    # the SARIF artifact URIs) to one machine
    assert found[0].path and not os.path.isabs(found[0].path)
    assert found[0].path.startswith("tests/")


def test_dropper_scan_fires_on_float_to_int_cast_and_warns_stop_gradient():
    import jax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.analysis.gradflow import dropper_findings

    def int_cast(x):
        return x.astype(jnp.int32).astype(jnp.float32) * 2.0

    jaxpr = jax.make_jaxpr(int_cast)(jnp.ones(4, jnp.float32))
    found = dropper_findings(jaxpr.jaxpr, "fixture/cast")
    assert [f.severity for f in found] == ["CRITICAL"]

    def stopped(x):
        return jax.lax.stop_gradient(x) * 2.0

    jaxpr = jax.make_jaxpr(stopped)(jnp.ones(4, jnp.float32))
    found = dropper_findings(jaxpr.jaxpr, "fixture/stop")
    assert [f.severity for f in found] == ["WARNING"]


def test_dropper_scan_quiet_off_the_tangent_path_and_under_custom_vjp():
    import jax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.analysis.gradflow import dropper_findings

    # bitcast feeding only a side computation that never reaches the
    # outputs' dataflow from the float inputs: int operand = not tainted
    def side(x, idx):
        shifted = jax.lax.bitcast_convert_type(idx, jnp.int32)
        return x * 2.0, shifted

    jaxpr = jax.make_jaxpr(side)(
        jnp.ones(4, jnp.float32), jnp.ones(4, jnp.uint32)
    )
    assert dropper_findings(jaxpr.jaxpr, "fixture/side") == []

    # the registered-VJP envelope is the documented fix and is exempt
    @jax.custom_vjp
    def packed(x):
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
        return jax.lax.bitcast_convert_type(u, jnp.float32)

    packed.defvjp(lambda x: (packed(x), None), lambda _, g: (g,))

    def wrapped(x):
        return packed(x) * 2.0

    jaxpr = jax.make_jaxpr(wrapped)(jnp.ones(4, jnp.float32))
    assert dropper_findings(jaxpr.jaxpr, "fixture/protected") == []


def test_real_packed_transport_runs_clean_and_exemption_is_alive():
    """The negative fixture of the ISSUE: the coalesced exchange's
    `_packed_transport` (registered VJP) scans clean — and the control
    proves the custom-vjp exemption is what keeps it clean (the bitcast
    transport IS there underneath)."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.analysis import gradflow, ir
    from implicitglobalgrid_tpu.ops import halo

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        gg = igg.get_global_grid()
        fields = ir.model_field_structs("porous", 8)

        def body(*fs):
            return halo.exchange_dims_multi(fs, (0, 1, 2), width=1,
                                            coalesce=True)

        jaxpr = ir.unwrap_inner(ir._trace_mapped(body, fields, gg).jaxpr)
    finally:
        igg.finalize_global_grid()

    assert gradflow.dropper_findings(jaxpr, "exchange/porous") == []

    # liveness control: descending past the protection must surface the
    # packed transport's bitcasts — the exemption does real work
    import pytest as _pytest

    mp = _pytest.MonkeyPatch()
    try:
        mp.setattr(gradflow, "_PROTECTED", ())
        unprotected = gradflow.dropper_findings(jaxpr, "exchange/porous")
    finally:
        mp.undo()
    assert any(
        f.code == "cotangent-dropper" and "bitcast" in f.anchor
        for f in unprotected
    )


class _StubGrad:
    def __init__(self, name, grad_n, primal_n):
        self.name = name
        self._counts = (grad_n, primal_n)

    def collective_counts(self):
        return self._counts


def test_backward_collective_census_separates_healthy_from_sunk():
    from implicitglobalgrid_tpu.analysis.gradflow import census_findings

    # healthy: VJP issues strictly more collectives than the primal
    assert census_findings([_StubGrad("grad/x", 66, 6)]) == []

    # the PR-5 failure shape: VJP count == primal count (no backward hops)
    sunk = census_findings([_StubGrad("grad/x", 5, 5)])
    assert [f.code for f in sunk] == ["cotangent-sink"]
    assert sunk[0].severity == "CRITICAL"

    # a primal with zero collectives means the census itself went blind
    blind = census_findings([_StubGrad("grad/x", 3, 0)])
    assert [f.code for f in blind] == ["census-broken"]


# -- changed-files ref mode (--changed-only=REF) ------------------------------


def _git_fixture_repo(tmp_path):
    import subprocess

    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True,
                       capture_output=True)
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo, capture_output=True,
            text=True,
        ).stdout.strip()

    git("init", "-q")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    (repo / "base.txt").write_text("base\n")
    git("add", "base.txt")
    base_sha = git("commit", "-qm", "base")
    git("checkout", "-qb", "feature")
    (repo / "feat.txt").write_text("feat\n")
    git("add", "feat.txt")
    git("commit", "-qm", "feat")
    return repo, base_sha


def test_changed_files_ref_mode_sees_committed_diffs(tmp_path):
    """On a CLEAN checkout `git status` selects nothing — the CI hole the
    satellite fixes; ref mode diffs against the merge-base instead, and the
    two censuses union when the worktree is dirty too."""
    from implicitglobalgrid_tpu.analysis.core import changed_files

    repo, base_sha = _git_fixture_repo(tmp_path)

    assert changed_files(str(repo)) == []  # clean checkout: status empty
    assert changed_files(str(repo), ref=base_sha) == ["feat.txt"]

    (repo / "dirty.txt").write_text("wip\n")  # untracked joins the union
    got = changed_files(str(repo), ref=base_sha)
    assert set(got) == {"feat.txt", "dirty.txt"}
    assert changed_files(str(repo)) == ["dirty.txt"]  # status mode unchanged


def test_changed_files_ref_mode_raises_on_bad_ref(tmp_path):
    """A bad ref must RAISE, not silently select zero analyzers — an empty
    census would green-light a PR that was never linted."""
    from implicitglobalgrid_tpu.analysis.core import changed_files

    repo, _ = _git_fixture_repo(tmp_path)
    with pytest.raises(RuntimeError, match="merge-base"):
        changed_files(str(repo), ref="no-such-ref-xyz")


# -- SARIF export -------------------------------------------------------------


def _sarif_fixture_report():
    from implicitglobalgrid_tpu.analysis.core import Finding, Report

    dropper = Finding(
        analyzer="grad-soundness", code="cotangent-dropper",
        severity="CRITICAL",
        message="fixture: bitcast on the tangent path",
        path="implicitglobalgrid_tpu/ops/halo.py", line=12,
        symbol="exchange/porous", anchor="bitcast[f32]",
        fix_hint="wrap the transport in jax.custom_vjp",
    )
    cost = Finding(
        analyzer="hlo-cost", code="cost-regression", severity="ERROR",
        message="fixture: payload bytes doubled",
        symbol="exchange/porous[coalesce=True]",
        anchor="collective_payload_bytes",
    )
    suppressed = Finding(
        analyzer="knob-binding", code="env-read-in-trace",
        severity="WARNING", message="fixture: traced knob read",
        path="implicitglobalgrid_tpu/ops/halo.py", symbol="f",
        anchor="IGG_FIXTURE",
    )
    return Report(
        findings=[cost, dropper],
        suppressed=[(suppressed, "documented per-call contract")],
        ran=["grad-soundness", "hlo-cost", "knob-binding"],
        skipped=["knob-decl"],
    )


def test_sarif_export_matches_the_golden_file():
    """The full artifact is pinned byte-for-byte (sorted keys, stable
    ordering, no timestamps) — CI consumers parse this exact shape, so any
    schema drift must be a reviewed diff of the golden file."""
    from implicitglobalgrid_tpu.analysis.sarif import report_to_sarif

    golden_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "data",
        "igg_lint_golden.sarif",
    )
    got = json.dumps(report_to_sarif(_sarif_fixture_report()), indent=2,
                     sort_keys=True) + "\n"
    with open(golden_path, encoding="utf-8") as f:
        assert got == f.read()


def test_sarif_results_carry_fingerprints_and_suppressions():
    from implicitglobalgrid_tpu.analysis.sarif import report_to_sarif

    report = _sarif_fixture_report()
    sarif = report_to_sarif(report)
    run0 = sarif["runs"][0]
    assert sarif["version"] == "2.1.0"
    assert run0["tool"]["driver"]["name"] == "igg-lint"

    results = run0["results"]
    assert len(results) == 3  # 2 active + 1 suppressed
    fps = {f.fingerprint for f in report.findings} | {
        f.fingerprint for f, _ in report.suppressed
    }
    assert {
        r["partialFingerprints"]["iggLintFingerprint/v1"] for r in results
    } == fps
    sup = [r for r in results if "suppressions" in r]
    assert len(sup) == 1
    assert sup[0]["suppressions"][0]["justification"] == (
        "documented per-call contract"
    )
    # CRITICAL maps to SARIF "error" but keeps its name in properties
    crit = next(r for r in results
                if r["ruleId"] == "grad-soundness/cotangent-dropper")
    assert crit["level"] == "error"
    assert crit["properties"]["iggSeverity"] == "CRITICAL"
    assert crit["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 12


def test_sarif_rule_level_is_worst_severity_regardless_of_order():
    """A rule spanning severities (cotangent-dropper: CRITICAL bitcast vs
    WARNING stop_gradient) must advertise its WORST case even when a
    milder finding appears first — rule metadata must not flip with
    finding order."""
    from implicitglobalgrid_tpu.analysis.core import Finding, Report
    from implicitglobalgrid_tpu.analysis.sarif import report_to_sarif

    def f(sev, anchor):
        return Finding(analyzer="grad-soundness", code="cotangent-dropper",
                       severity=sev, message="m", symbol="s", anchor=anchor)

    report = Report(findings=[f("WARNING", "stop_gradient"),
                              f("CRITICAL", "bitcast")],
                    ran=["grad-soundness"])
    rule = report_to_sarif(report)["runs"][0]["tool"]["driver"]["rules"][0]
    assert rule["defaultConfiguration"]["level"] == "error"
