"""Seeded fixtures for the `igg.analysis` suite (docs/static-analysis.md).

Each analyzer is pinned BOTH ways: a deliberately-broken fixture it must
fire on (a rank-divergent collective, a knob read inside jit, a bogus
alias, a malformed perm), and a clean twin it must stay quiet on — an
analyzer that cannot tell the two apart is a broken lint, not a clean
tree.  The framework itself (fingerprints, baseline workflow, changed-only
selection, exit codes) is tested here too; the real package's full-suite
run lives in `tests/test_lint_suite.py`.
"""

import json
import os
import sys
import textwrap

import pytest

from implicitglobalgrid_tpu.analysis import core
from implicitglobalgrid_tpu.analysis.core import (
    Baseline,
    Context,
    Finding,
    Report,
    select_for_paths,
)


def _fixture_ctx(tmp_path, sources: dict) -> Context:
    """A Context whose package root is a throwaway package built from
    ``{relative path: source}`` — the AST passes scan it instead of the
    real package."""
    pkg = tmp_path / "fixture_pkg"
    for rel, src in sources.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Context(repo_root=str(tmp_path), package_root=str(pkg))


# -- framework: Finding / fingerprints ---------------------------------------


def test_finding_rejects_unknown_severity():
    with pytest.raises(ValueError, match="severity"):
        Finding(analyzer="a", code="c", severity="FATAL", message="m")


def test_fingerprint_survives_message_and_line_drift():
    a = Finding(analyzer="a", code="c", severity="ERROR", message="old",
                path="p.py", line=10, symbol="f", anchor="K")
    b = Finding(analyzer="a", code="c", severity="ERROR", message="reworded",
                path="p.py", line=99, symbol="f", anchor="K")
    c = Finding(analyzer="a", code="c", severity="ERROR", message="old",
                path="p.py", line=10, symbol="f", anchor="OTHER")
    assert a.fingerprint == b.fingerprint
    assert a.fingerprint != c.fingerprint


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(
        {"suppressions": [{"fingerprint": "abc", "justification": "  "}]}
    ))
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(path))
    path.write_text(json.dumps(
        {"suppressions": [{"fingerprint": "abc",
                           "justification": "documented contract"}]}
    ))
    base = Baseline.load(str(path))
    f = Finding(analyzer="a", code="c", severity="ERROR", message="m")
    assert base.match(f) is None
    assert "abc" in base.suppressions


def test_shipped_baseline_is_well_formed():
    base = Baseline.load(core.DEFAULT_BASELINE)
    assert base.suppressions, "the shipped baseline lost its entries"
    for entry in base.suppressions.values():
        assert len(entry["justification"]) > 40  # a reason, not a mute


def test_report_exit_codes():
    err = Finding(analyzer="a", code="c", severity="ERROR", message="m")
    warn = Finding(analyzer="a", code="c", severity="WARNING", message="m")
    assert Report().exit_code() == 0
    assert Report(findings=[warn]).exit_code() == 0
    assert Report(findings=[warn]).exit_code(strict=True) == 1
    assert Report(findings=[err]).exit_code() == 1
    assert Report(errors={"a": "boom"}).exit_code() == 2


# -- framework: runner + baseline + changed-only ------------------------------


def _register_fake_analyzer(tmp_path, monkeypatch, body: str,
                            modname: str = "igg_fake_pass"):
    """Install a one-analyzer registry whose pass is ``body`` (a module
    defining ``run(ctx)``), returning its name.  ``modname`` must be
    unique per test — `AnalyzerSpec.load` goes through the import cache."""
    mod = tmp_path / f"{modname}.py"
    mod.write_text(textwrap.dedent(body))
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.delitem(sys.modules, modname, raising=False)
    spec = core.AnalyzerSpec(
        name="fake", module=modname, func="run", title="fixture",
        paths=("implicitglobalgrid_tpu/ops/**",),
    )
    monkeypatch.setattr(core, "REGISTRY", {"fake": spec})
    return "fake"


_FAKE_PASS = """
    from implicitglobalgrid_tpu.analysis.core import Finding

    def run(ctx):
        yield Finding(analyzer="fake", code="seeded", severity="ERROR",
                      message="seeded finding", symbol="s", anchor="a")
"""


def test_run_reports_and_baselines_and_flags_stale(tmp_path, monkeypatch):
    _register_fake_analyzer(tmp_path, monkeypatch, _FAKE_PASS)
    report = core.run(baseline=None)
    assert [f.code for f in report.findings] == ["seeded"]
    assert report.exit_code() == 1

    fp = report.findings[0].fingerprint
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"suppressions": [
        {"fingerprint": fp, "justification": "seeded fixture, intentional"},
        {"fingerprint": "dead0000dead0000",
         "justification": "left over from a removed pass"},
    ]}))
    report = core.run(baseline=str(base))
    assert report.findings == []
    assert report.exit_code() == 0  # suppressed + stale do not fail
    assert [f.fingerprint for f, _ in report.suppressed] == [fp]
    assert report.stale_suppressions == ["dead0000dead0000"]
    assert "matched no finding" in report.human()


def test_run_changed_only_selects_by_declared_paths(tmp_path, monkeypatch):
    _register_fake_analyzer(tmp_path, monkeypatch, _FAKE_PASS)
    hit = core.run(baseline=None,
                   changed_paths=["implicitglobalgrid_tpu/ops/halo.py"])
    assert hit.ran == ["fake"] and len(hit.findings) == 1
    miss = core.run(baseline=None, changed_paths=["docs/usage.md"])
    assert miss.ran == [] and miss.skipped == ["fake"]
    assert miss.findings == []


def test_run_keep_going_traps_analyzer_crashes(tmp_path, monkeypatch):
    _register_fake_analyzer(
        tmp_path, monkeypatch,
        "def run(ctx):\n    raise RuntimeError('boom')\n",
        modname="igg_fake_crashing_pass",
    )
    with pytest.raises(RuntimeError, match="boom"):
        core.run(baseline=None)
    report = core.run(baseline=None, keep_going=True)
    assert "RuntimeError: boom" in report.errors["fake"]
    assert report.exit_code() == 2


def test_run_rejects_unknown_analyzer():
    with pytest.raises(ValueError, match="unknown analyzer"):
        core.run(["no-such-pass"])


def test_changed_only_selection_of_the_real_registry():
    # Framework changes select everything; subsystem paths select their
    # declared analyzers; unrelated paths select nothing.
    assert set(select_for_paths(["scripts/igg_lint.py"])) == set(core.REGISTRY)
    ops = select_for_paths(["implicitglobalgrid_tpu/ops/halo.py"])
    assert "collective-consistency" in ops and "collective-budget" in ops
    docs = select_for_paths(["docs/usage.md"])
    assert docs == ["knob-decl"]
    assert select_for_paths(["README.md"]) == []


# -- collective-consistency: rank census --------------------------------------


def _census(sequences):
    from implicitglobalgrid_tpu.analysis.ir import RankCensus

    return RankCensus(name="fixture", sequences=sequences)


def test_divergence_detector_fires_on_rank_divergent_collective():
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
    )

    op_a = ("ppermute", ("x",), ("f32[8]",))
    op_b = ("psum", ("x",), ("f32[8]",))
    # rank 1 swaps the op kind at position 1 — the deadlock class
    found = check_rank_consistency(
        _census({0: (op_a, op_b), 1: (op_a, op_a)})
    )
    assert [f.code for f in found] == ["rank-divergent-sequence"]
    assert found[0].severity == "CRITICAL"
    assert "op 1" in found[0].message


def test_divergence_detector_fires_on_sequence_length_mismatch():
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
    )

    op = ("ppermute", ("x",), ("f32[8]",))
    found = check_rank_consistency(_census({0: (op, op), 1: (op,)}))
    assert len(found) == 1
    assert "2 collective(s)" in found[0].message


def test_divergence_detector_quiet_on_identical_sequences():
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
    )

    op = ("ppermute", ("x",), ("f32[8]",))
    assert check_rank_consistency(
        _census({r: (op, op) for r in range(8)})
    ) == []
    assert check_rank_consistency(_census({})) == []


def test_census_provider_registration_feeds_the_detector():
    from implicitglobalgrid_tpu.analysis import collectives as C

    def provider(ctx):
        yield _census({0: (("psum", ("x",), ("f32[4]",)),), 1: ()})

    C.register_census_provider(provider)
    try:
        found = C.host_plan_findings(Context())
    finally:
        C.CENSUS_PROVIDERS.remove(provider)
    assert any(
        f.code == "rank-divergent-sequence" and f.symbol == "fixture"
        for f in found
    )


def test_gather_plan_census_is_clean_and_covers_the_real_plan():
    """The PR-1 flaky-gather watch item as a static invariant: the real
    `collective_plan` must be rank-independent over the census configs."""
    from implicitglobalgrid_tpu.analysis import collectives as C

    censuses = list(C.gather_plan_censuses(Context()))
    assert len(censuses) == len(C._GATHER_PLAN_CONFIGS)
    for census in censuses:
        assert C.check_rank_consistency(census) == []
        # every simulated rank present, root included
        assert len(census.sequences) >= 1


def test_gather_collective_plan_ignores_is_root_and_covers_ragged_tail():
    import numpy as np

    from implicitglobalgrid_tpu.ops.gather import collective_plan

    dims, batch = (3, 2), 4  # 6 blocks, batch 4 -> one ragged tail of 2
    root_plan = collective_plan(dims, batch, is_root=True)
    assert root_plan == collective_plan(dims, batch, is_root=False)
    sizes = [len(sels) for _, sels in root_plan]
    assert sizes == [4, 2]
    flat = [s for _, sels in root_plan for s in sels]
    assert flat == list(range(int(np.prod(dims))))


# -- collective-consistency: AST rank-guard pass ------------------------------


_GUARDED = """
    from jax import lax

    def exchange(x, rank):
        if rank == 0:
            x = lax.psum(x, "x")
        return x
"""

_CLEAN = """
    from jax import lax

    def exchange(x, rank):
        x = lax.psum(x, "x")          # every rank, unconditionally
        if rank == 0:
            x = x * 2                 # rank-dependent HOST math is fine
        if x.ndim == 3:
            x = lax.pmax(x, "x")      # non-rank predicate is fine
        return x
"""


def test_rank_guard_pass_fires_on_guarded_collective(tmp_path):
    from implicitglobalgrid_tpu.analysis import collectives as C

    ctx = _fixture_ctx(tmp_path, {"mod.py": _GUARDED})
    found = C.ast_findings(ctx)
    assert [f.code for f in found] == ["rank-guarded-collective"]
    f = found[0]
    assert f.severity == "CRITICAL" and f.symbol == "exchange"
    assert f.anchor == "psum" and "rank" in f.message


def test_rank_guard_pass_quiet_on_unconditional_collective(tmp_path):
    from implicitglobalgrid_tpu.analysis import collectives as C

    ctx = _fixture_ctx(tmp_path, {"mod.py": _CLEAN})
    assert C.ast_findings(ctx) == []


def test_rank_guard_pass_sees_the_early_return_form(tmp_path):
    """The commonest shape of the PR-1 divergence: non-roots bail out
    BEFORE the collective, so the collective sits after the guard, not
    inside it."""
    from implicitglobalgrid_tpu.analysis import collectives as C

    src = """
        from jax import lax

        def exchange(x, rank):
            if rank != 0:
                return x
            return lax.psum(x, "x")
    """
    found = C.ast_findings(_fixture_ctx(tmp_path / "pos", {"m.py": src}))
    assert [f.code for f in found] == ["rank-guarded-collective"]
    assert "rank" in found[0].message

    # early return on a NON-rank predicate stays quiet
    quiet = """
        from jax import lax

        def exchange(x):
            if x.ndim != 3:
                return x
            return lax.psum(x, "x")
    """
    assert C.ast_findings(
        _fixture_ctx(tmp_path / "neg", {"q.py": quiet})
    ) == []


def test_rank_guard_pass_sees_ternaries_and_nested_guards(tmp_path):
    from implicitglobalgrid_tpu.analysis import collectives as C

    src = """
        from jax import lax

        def f(x, gg):
            return lax.psum(x, "x") if gg.coords[0] == 0 else x
    """
    found = C.ast_findings(_fixture_ctx(tmp_path, {"m.py": src}))
    assert [f.code for f in found] == ["rank-guarded-collective"]
    assert "coords" in found[0].message


# -- collective-consistency: traced-census structure checks -------------------


class _StubEntry:
    name = "stub"
    mesh_shape = {"x": 2}

    def __init__(self, ops):
        self._ops = ops

    def collectives(self):
        return self._ops


def _op(perm, path=(), kind="ppermute"):
    from implicitglobalgrid_tpu.analysis.ir import CollectiveOp

    return CollectiveOp(kind=kind, axes=("x",), perm=perm, payload_bytes=0,
                        shapes=("f32[4]",), path=path)


def test_perm_checks_fire_on_malformed_permutes():
    from implicitglobalgrid_tpu.analysis.collectives import _perm_findings

    dup_src = _perm_findings(_StubEntry([_op(((0, 1), (0, 0)))]))
    assert [f.code for f in dup_src] == ["malformed-permute"]
    assert "duplicate sources" in dup_src[0].message

    dup_dst = _perm_findings(_StubEntry([_op(((0, 1), (1, 1)))]))
    assert "duplicate targets" in dup_dst[0].message

    oob = _perm_findings(_StubEntry([_op(((0, 5),))]))
    assert "outside the axis size" in oob[0].message


def test_perm_checks_fire_on_collective_under_cond():
    from implicitglobalgrid_tpu.analysis.collectives import _perm_findings

    found = _perm_findings(
        _StubEntry([_op(((0, 1), (1, 0)), path=("while", "cond"))])
    )
    assert [f.code for f in found] == ["collective-under-cond"]
    assert found[0].severity == "CRITICAL"


def test_perm_checks_quiet_on_valid_partial_permutation():
    from implicitglobalgrid_tpu.analysis.collectives import _perm_findings

    # a PROC_NULL-masked edge hop: partial perm, no dup, in range
    assert _perm_findings(_StubEntry([_op(((0, 1),))])) == []


# -- knob-binding -------------------------------------------------------------


_KNOB_IN_TRACE = """
    import os
    from jax import jit

    def body(x):
        scale = int(os.environ.get("IGG_FIXTURE_SCALE", "1"))
        return x * scale

    stepper = jit(body)
"""

_KNOB_HOST_SIDE = """
    import os
    from jax import jit

    def _scale():
        return int(os.environ.get("IGG_FIXTURE_SCALE", "1"))

    def make_step():
        scale = _scale()              # resolved HOST-side, then closed over

        def body(x):
            return x * scale

        return jit(body)
"""


def test_knob_binding_fires_on_env_read_inside_jit(tmp_path):
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    found = run_knob_binding(_fixture_ctx(tmp_path, {"m.py": _KNOB_IN_TRACE}))
    assert [f.code for f in found] == ["env-read-in-trace"]
    f = found[0]
    assert f.anchor == "IGG_FIXTURE_SCALE" and f.severity == "ERROR"
    assert "TRACE time" in f.message


def test_knob_binding_quiet_when_knob_resolved_host_side(tmp_path):
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    found = run_knob_binding(
        _fixture_ctx(tmp_path, {"m.py": _KNOB_HOST_SIDE})
    )
    assert found == []


def test_knob_binding_follows_calls_and_accessor_args(tmp_path):
    """The package idiom: a traced closure calling an accessor that calls
    the generic reader — the knob name rides the constant first arg."""
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    src = """
        import os
        from jax import lax
        from .cfg import int_env

        def make(n):
            def inner(c, x):
                return c, x * int_env("IGG_FIXTURE_DEPTH")

            def body(x):
                return lax.scan(inner, 0, x)

            return body
    """
    cfg = """
        import os

        def int_env(name):
            return int(os.environ.get(name, "0"))
    """
    found = run_knob_binding(
        _fixture_ctx(tmp_path, {"m.py": src, "cfg.py": cfg})
    )
    assert [f.anchor for f in found] == ["IGG_FIXTURE_DEPTH"]


def test_real_package_knob_binding_matches_the_baseline():
    """Triage pin: every knob-binding finding on the REAL package is one of
    the three baselined per-trace contracts — a new traced env read must
    show up here (and fail tier-1 via test_lint_suite) until triaged."""
    from implicitglobalgrid_tpu.analysis.knobs import run_knob_binding

    base = Baseline.load(core.DEFAULT_BASELINE)
    found = run_knob_binding(Context())
    unbaselined = [f for f in found if base.match(f) is None]
    assert unbaselined == [], [f.message for f in unbaselined]
    assert {f.anchor for f in found} == {
        "IGG_COALESCE", "IGG_TELEMETRY", "IGG_VMEM_MB",
    }


# -- knob-decl ----------------------------------------------------------------


def test_knob_decl_fires_on_undeclared_and_undocumented(tmp_path):
    from implicitglobalgrid_tpu.analysis.knobs import knob_decl_findings

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text('import os\nos.environ.get("IGG_BOGUS")\n')
    config = tmp_path / "config.py"
    config.write_text('"""knob table: (none)"""\n')
    usage = tmp_path / "usage.md"
    usage.write_text("# usage\n")
    found = knob_decl_findings(str(tmp_path), str(pkg), str(config),
                               str(usage))
    assert sorted(f.code for f in found) == [
        "undeclared-knob", "undocumented-knob",
    ]
    assert all(f.symbol == "IGG_BOGUS" for f in found)

    config.write_text('"""table: IGG_BOGUS"""\n')
    usage.write_text("| `IGG_BOGUS` | fixture row |\n")
    assert knob_decl_findings(str(tmp_path), str(pkg), str(config),
                              str(usage)) == []


# -- pallas-aliasing ----------------------------------------------------------


def test_alias_pair_validation_fires_on_bogus_pairs():
    from implicitglobalgrid_tpu.analysis.aliasing import validate_alias_pairs

    a = ((8, 8), "float32")
    b = ((8, 9), "float32")
    assert validate_alias_pairs([(0, 0)], [a], [a]) == []
    assert "out of range" in validate_alias_pairs([(2, 0)], [a], [a])[0]
    assert "out of range" in validate_alias_pairs([(0, 3)], [a], [a])[0]
    probs = validate_alias_pairs([(0, 0), (1, 0)], [a, a], [a])
    assert any("two inputs" in p for p in probs)
    probs = validate_alias_pairs([(0, 0)], [b], [a])
    assert any("shape+dtype" in p for p in probs)


_BAD_ALIAS = """
    import jax.experimental.pallas as pl

    def build(kernel, shapes):
        return pl.pallas_call(
            kernel, out_shape=shapes,
            input_output_aliases={0: 0, 1: 0},
        )
"""

_GOOD_ALIAS = """
    import jax.experimental.pallas as pl

    def build(kernel, shapes):
        return pl.pallas_call(
            kernel, out_shape=shapes,
            input_output_aliases={0: 0, 1: 1},
        )
"""


def test_aliasing_ast_pass_fires_on_duplicate_output_alias(tmp_path):
    from implicitglobalgrid_tpu.analysis import aliasing

    found = aliasing.ast_findings(
        _fixture_ctx(tmp_path, {"k.py": _BAD_ALIAS})
    )
    assert [f.code for f in found] == ["bad-alias-literal"]
    assert "two inputs on one" in found[0].message


def test_aliasing_ast_pass_quiet_on_injective_alias(tmp_path):
    from implicitglobalgrid_tpu.analysis import aliasing

    assert aliasing.ast_findings(
        _fixture_ctx(tmp_path, {"k.py": _GOOD_ALIAS})
    ) == []


def test_aliasing_ast_pass_fires_on_negative_donation(tmp_path):
    from implicitglobalgrid_tpu.analysis import aliasing

    src = """
        from jax import jit

        def make(f):
            return jit(f, donate_argnums=(-1,))
    """
    found = aliasing.ast_findings(_fixture_ctx(tmp_path, {"d.py": src}))
    assert [f.code for f in found] == ["bad-donate-literal"]


# -- overlap-independence -----------------------------------------------------


def _shard_mapped_jaxpr(body, nargs=1):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from implicitglobalgrid_tpu.analysis.ir import unwrap_inner
    from implicitglobalgrid_tpu.utils.compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))
    mapped = shard_map(body, mesh=mesh, in_specs=(P("x"),) * nargs,
                       out_specs=(P("x"),) * nargs, check_vma=False)
    args = (jnp.zeros((8,), jnp.float32),) * nargs
    return unwrap_inner(jax.make_jaxpr(mapped)(*args).jaxpr)


def test_independence_pairs_counts_dataflow_freedom():
    from jax import lax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.analysis.ir import independence_pairs

    ring = [(0, 1), (1, 0)]
    is_k = lambda e: e.primitive.name == "sin"  # noqa: E731

    def dependent(x):
        return (jnp.sin(lax.ppermute(x, "x", ring)),)

    pairs, nk, nc = independence_pairs(
        _shard_mapped_jaxpr(dependent), is_kernel=is_k
    )
    assert (pairs, nk, nc) == (0, 1, 1)

    def independent(x, z):
        return jnp.sin(x), lax.ppermute(z, "x", ring)

    pairs, nk, nc = independence_pairs(
        _shard_mapped_jaxpr(independent, nargs=2), is_kernel=is_k
    )
    assert (pairs, nk, nc) == (1, 1, 1)


def test_eqn_presence_classifies_collective_envelopes():
    """A pjit/custom-vjp envelope whose body is all collectives must join
    the census as a collective (the coalesced `_packed_transport` shape);
    one containing none of either stays out."""
    import jax
    from jax import lax

    from implicitglobalgrid_tpu.analysis.ir import _eqn_presence

    ring = [(0, 1), (1, 0)]

    def body(x):
        wrapped = jax.jit(lambda v: lax.ppermute(v, "x", ring))
        return (wrapped(x) + 1.0,)

    jaxpr = _shard_mapped_jaxpr(body)
    by_name = {e.primitive.name: e for e in jaxpr.eqns}
    assert _eqn_presence(by_name["pjit"]) == (False, True)
    assert _eqn_presence(by_name["add"]) == (False, False)


# -- collective-budget --------------------------------------------------------


def _hlo_fixture(n_perm: int, *, bad_start: bool = False) -> str:
    """Synthetic optimized-HLO text with ``n_perm`` collective-permutes in
    the shape `utils.hlo_analysis.collective_payloads` parses."""
    lines = ["ENTRY %main (p0: f32[6,6]) -> f32[6,6] {",
             "  %p0 = f32[6,6]{1,0} parameter(0)"]
    for i in range(n_perm):
        lines.append(
            f"  %cp{i} = f32[6,6]{{1,0}} collective-permute(%p0), "
            f"source_target_pairs={{{{0,1}},{{1,0}}}}"
        )
    if bad_start:
        # async-start whose tuple halves do NOT match -> raw-sum fallback
        lines.append(
            "  %cps = (f32[6,6]{1,0}, f32[4,6]{1,0}, u32[]) "
            "collective-permute-start(%p0), source_target_pairs={{0,1}}"
        )
    lines += ["  ROOT %r = f32[6,6]{1,0} add(%p0, %p0)", "}"]
    return "\n".join(lines)


def test_hlo_budget_cross_check_fires_and_stays_quiet():
    from implicitglobalgrid_tpu.analysis.budget import hlo_budget_findings

    # porous budget: 1 pair x 3 dims = 6 permutes allowed
    assert hlo_budget_findings(_hlo_fixture(6)) == []

    over = hlo_budget_findings(_hlo_fixture(8))
    assert [f.code for f in over] == ["hlo-budget-exceeded"]
    assert "split the coalesced hops" in over[0].message

    empty = hlo_budget_findings(_hlo_fixture(0))
    assert "hlo-census-broken" in [f.code for f in empty]


def test_hlo_budget_cross_check_flags_unaccounted_payloads():
    from implicitglobalgrid_tpu.analysis.budget import hlo_budget_findings

    found = hlo_budget_findings(_hlo_fixture(5, bad_start=True))
    assert [f.code for f in found] == ["hlo-payload-fallback"]
    assert found[0].severity == "WARNING"


def test_entry_budget_census_fires_on_per_field_regression():
    """The suite path counts the SHARED traced entries: a coalesce=True
    entry showing per-field collective counts must fire, and a control
    entry that lost its collectives must flag the census itself."""
    from implicitglobalgrid_tpu.analysis.budget import entry_budget_findings

    from implicitglobalgrid_tpu.analysis.ir import CollectiveOp

    def entry(name, axis_counts):
        ops = []
        for axis, cnt in axis_counts.items():
            ops += [
                CollectiveOp(kind="ppermute", axes=(axis,), perm=((0, 1),),
                             payload_bytes=0, shapes=("f32[4]",), path=())
            ] * cnt
        stub = _StubEntry(ops)
        stub.name = name
        return stub

    # diffusion (1 field): coalesced entry regressed to 6 permutes in x
    found = entry_budget_findings(
        [
            entry("exchange/diffusion[coalesce=True]", {"x": 6, "y": 2, "z": 2}),
            entry("exchange/diffusion[coalesce=False]", {"x": 2}),
        ],
        budget_pairs={"diffusion": 1},
    )
    assert [f.code for f in found] == ["budget-exceeded"]
    assert found[0].symbol == "diffusion/dim0"

    # clean twin stays quiet
    assert entry_budget_findings(
        [
            entry("exchange/diffusion[coalesce=True]", {"x": 2, "y": 2, "z": 2}),
            entry("exchange/diffusion[coalesce=False]", {"x": 2}),
        ],
        budget_pairs={"diffusion": 1},
    ) == []

    # a missing entry is a broken census, not a clean run
    assert [
        f.code
        for f in entry_budget_findings([], budget_pairs={"diffusion": 1})
    ] == ["census-broken"]


def test_budget_analyzer_fires_when_budget_tightened_to_zero():
    """Liveness: with an impossible budget the census must report every
    exchanged dimension — proving it sees the real collectives (the clean
    run on the true budget is tier-1's test_collective_budget)."""
    from implicitglobalgrid_tpu.analysis.budget import budget_findings

    found = budget_findings(budget_pairs={"diffusion": 0})
    assert [f.code for f in found] == ["budget-exceeded"] * 3
    assert {f.symbol for f in found} == {
        "diffusion/dim0", "diffusion/dim1", "diffusion/dim2",
    }
