"""Perf-regression gate: `analysis.perf` + ``scripts/check_perf.py`` +
``scripts/refresh_cost_baseline.py`` (docs/performance.md, ROADMAP item 5).

The acceptance bar of ISSUE 7: the gate exits nonzero on a doctored BENCH
record outside tolerance and 0 on the real committed trajectory — turning
the hand-run bench evidence into the same kind of invariant the collective
budget already is.  The refresh helper's audit contract (a ``--justify``
note per changed metric, mirroring ``analysis/baseline.json``) is pinned
here too; the committed cost baseline itself is pinned by
``tests/test_lint_suite.py`` (the full-suite ``hlo-cost`` comparison).
"""

import copy
import importlib.util
import json
import os

import pytest

from implicitglobalgrid_tpu.analysis import perf

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_repo, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


check_perf = _load_script("check_perf")
refresh_cost_baseline = _load_script("refresh_cost_baseline")


# -- record parsing -----------------------------------------------------------


def test_trajectory_loads_and_skips_unrecoverable_rounds():
    """The committed trajectory: r02-r04 parse (driver wrapper with
    ``parsed``), r01/r05 are truncated beyond recovery and must be SKIPPED
    with a report, never silently used."""
    records, skipped = perf.load_bench_records(_repo)
    rounds = [r for r, _ in records]
    assert rounds == sorted(rounds)
    assert len(records) >= 2, "the gate needs at least two parseable rounds"
    for _, rec in records:
        assert "extras" in rec
    assert all(s.startswith("BENCH_r") for s in skipped)


def test_parse_bench_file_accepts_wrapper_raw_and_rejects_garbage(tmp_path):
    raw = {"metric": "m", "value": 1.0, "extras": {"a": {"teff": 2.0}}}
    p = tmp_path / "raw.json"
    p.write_text(json.dumps(raw))
    assert perf.parse_bench_file(str(p))["value"] == 1.0

    wrapped = tmp_path / "wrapped.json"
    wrapped.write_text(json.dumps({"n": 1, "parsed": raw, "tail": ""}))
    assert perf.parse_bench_file(str(wrapped))["value"] == 1.0

    tail = tmp_path / "tail.json"
    tail.write_text(json.dumps({"n": 1, "tail": "log noise " + json.dumps(raw)}))
    assert perf.parse_bench_file(str(tail))["value"] == 1.0

    # trailing log text AFTER the record (a normal capture shape) must not
    # make a fully-present record "unparseable"
    trailing = tmp_path / "trailing.json"
    trailing.write_text(json.dumps(
        {"n": 1, "tail": "noise " + json.dumps(raw) + " exited 0\n"}
    ))
    assert perf.parse_bench_file(str(trailing))["value"] == 1.0

    trunc = tmp_path / "trunc.json"
    trunc.write_text(json.dumps({"n": 1, "tail": 'noise {"metric": "m", "va'}))
    assert perf.parse_bench_file(str(trunc)) is None

    # a file killed mid-write is not even valid top-level JSON: still a
    # skip-and-report, never a crash
    killed = tmp_path / "killed.json"
    killed.write_text('{"n": 6, "tail": "trunc')
    assert perf.parse_bench_file(str(killed)) is None


def test_registry_pass_flags_unparseable_rounds(tmp_path):
    """A committed round the gate cannot read is a blind spot — it must
    surface as an ERROR finding (baselined for the historical r01/r05),
    not vanish into a skipped list nobody reads: otherwise a regressed
    record merges wearing truncation as camouflage."""

    class _Ctx:
        repo_root = str(tmp_path)

    records, _ = perf.load_bench_records(_repo)
    for i, (_, rec) in enumerate(records[-2:], start=2):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(json.dumps(rec))
    (tmp_path / "BENCH_r06.json").write_text('{"n": 6, "tail": "trunc')

    findings = perf.run(_Ctx())
    bad = [f for f in findings if f.code == "unparseable-record"]
    assert [f.symbol for f in bad] == ["BENCH_r06.json"]
    assert all(f.severity == "ERROR" for f in bad)

    # the other escape hatch: a gated metric DELETED from the newest round
    # must fire (here: the porous config r04 retired from r03's set)
    vanished = [f for f in findings if f.code == "metric-vanished"]
    assert [f.anchor for f in vanished] == [
        "porous_256_pallas_fused.npt10_w2.teff"
    ]
    assert all(f.severity == "ERROR" for f in vanished)

    # the real repo's historical truncations AND the r04 config
    # retirement carry baseline entries
    from implicitglobalgrid_tpu.analysis.core import Baseline, Context

    base = Baseline.load(os.path.join(
        _repo, "implicitglobalgrid_tpu", "analysis", "baseline.json"))
    repo_findings = [f for f in perf.run(Context())
                     if f.code in ("unparseable-record", "metric-vanished")]
    assert sorted((f.code, f.symbol) for f in repo_findings) == [
        ("metric-vanished", "r04"),
        ("unparseable-record", "BENCH_r01.json"),
        ("unparseable-record", "BENCH_r05.json"),
    ]
    for f in repo_findings:
        assert base.match(f), (
            f"{f.code} on {f.symbol} lost its baseline entry"
        )


def test_gate_metrics_selects_throughput_not_wall_time():
    rec = {
        "value": 10.0,
        "extras": {
            "diffusion_xla": {"teff": 20.0, "t_it_ms": 5.0},
            "grad": {"teff_grad": 7.0, "t_fwd_ms": 1.0},
            "broken": {"error": "ValueError: boom"},
            "nested": {"inner": {"teff": 3.0}},
        },
    }
    assert perf.gate_metrics(rec) == {
        "headline": 10.0,
        "diffusion_xla.teff": 20.0,
        "grad.teff_grad": 7.0,
        "nested.inner.teff": 3.0,
    }


def test_gate_metrics_maps_batch_members_per_s():
    """ISSUE 8: the ``bench.py batch`` record's members/s/chip metrics are
    gated — every sweep row and the headline rate — so a batching
    regression fails the bench-regression pass like a bandwidth drop."""
    rec = {
        "extras": {
            "batch_ensemble": {
                "members_per_s": 12.0,
                "throughput_multiplier": 6.1,  # not a gated key
                "sweep": {
                    "B1": {"members_per_s": 2.0, "t_step_ms": 1.0},
                    "B8": {"members_per_s": 12.0, "t_step_ms": 1.3},
                },
            },
        },
    }
    assert perf.gate_metrics(rec) == {
        "batch_ensemble.members_per_s": 12.0,
        "batch_ensemble.sweep.B1.members_per_s": 2.0,
        "batch_ensemble.sweep.B8.members_per_s": 12.0,
    }
    assert "members_per_s" in perf.GATED_KEYS


# -- comparison + waivers -----------------------------------------------------


def test_compare_metrics_one_sided_band():
    ref = {"a.teff": 100.0, "b.teff": 100.0, "gone.teff": 1.0}
    cand = {"a.teff": 90.0, "b.teff": 80.0, "new.teff": 5.0}
    cmp = perf.compare_metrics(cand, ref, tol=0.15, waivers=[])
    assert [r["metric"] for r in cmp["regressions"]] == ["b.teff"]
    assert cmp["missing"] == ["gone.teff"]
    assert cmp["checked"] == 2
    # improvements never fail (one-sided: the reference simply rises)
    up = perf.compare_metrics({"a.teff": 500.0}, {"a.teff": 100.0},
                              waivers=[])
    assert up["regressions"] == []


def test_waivers_are_measured_concessions_not_mute_buttons(tmp_path):
    ref, cand = {"a.teff": 100.0}, {"a.teff": 50.0}
    waiver = {"metric": "a.teff", "justification": "chip tenancy drift",
              "max_drop": 0.6}
    cmp = perf.compare_metrics(cand, ref, waivers=[waiver])
    assert cmp["regressions"] == [] and len(cmp["waived"]) == 1
    assert cmp["waived"][0]["justification"] == "chip tenancy drift"

    # a drop beyond the waiver's own bound still fails
    tight = dict(waiver, max_drop=0.2)
    cmp = perf.compare_metrics(cand, ref, waivers=[tight])
    assert [r["metric"] for r in cmp["regressions"]] == ["a.teff"]

    # round-scoped waivers only cover their rounds
    scoped = dict(waiver, rounds=[9])
    cmp = perf.compare_metrics(cand, ref, waivers=[scoped],
                               candidate_round=4)
    assert len(cmp["regressions"]) == 1
    cmp = perf.compare_metrics(cand, ref, waivers=[scoped],
                               candidate_round=9)
    assert len(cmp["waived"]) == 1
    # ...and a FRESH record (no round) must not inherit a concession
    # granted to a historical dip
    cmp = perf.compare_metrics(cand, ref, waivers=[scoped],
                               candidate_round=None)
    assert len(cmp["regressions"]) == 1 and not cmp["waived"]

    # the audit contract: no justification = hard error
    bad = tmp_path / "waivers.json"
    bad.write_text(json.dumps(
        {"waivers": [{"metric": "a.teff", "justification": " "}]}
    ))
    with pytest.raises(ValueError, match="justification"):
        perf.load_waivers(str(bad))
    assert perf.load_waivers(str(tmp_path / "absent.json")) == []


def test_shipped_waiver_file_is_well_formed():
    for w in perf.load_waivers():
        assert w["justification"].strip()


# -- the bench.py hook --------------------------------------------------------


def test_gate_summary_verdict_for_fresh_records(tmp_path):
    records, _ = perf.load_bench_records(_repo)
    _, newest = records[-1]
    ok = perf.gate_summary(copy.deepcopy(newest), _repo)
    assert ok["ok"] is True and ok["reference_round"] == records[-1][0]

    doctored = copy.deepcopy(newest)
    doctored["value"] = float(doctored["value"]) * 0.5
    bad = perf.gate_summary(doctored, _repo)
    assert bad["ok"] is False
    assert any(r["metric"] == "headline" for r in bad["regressions"])

    # an empty trajectory cannot regress (first bench run of a repo)
    first = perf.gate_summary(copy.deepcopy(newest), str(tmp_path))
    assert first["ok"] is True and "note" in first


# -- check_perf CLI (the PR gate) ---------------------------------------------


def test_check_perf_passes_the_real_trajectory(capsys):
    """Acceptance: exit 0 on the committed rounds as they stand."""
    assert check_perf.main([]) == 0
    out = capsys.readouterr().out
    assert "check_perf: OK" in out


def test_check_perf_fails_a_doctored_record(tmp_path, capsys):
    """Acceptance: a candidate whose headline halved exits nonzero."""
    records, _ = perf.load_bench_records(_repo)
    _, newest = records[-1]
    doctored = copy.deepcopy(newest)
    doctored["value"] = float(doctored["value"]) * 0.5
    p = tmp_path / "doctored.json"
    p.write_text(json.dumps(doctored))
    assert check_perf.main(["--candidate", str(p)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION headline" in out

    # within tolerance: a 5% dip is chip-tenancy noise, not a regression
    mild = copy.deepcopy(newest)
    mild["value"] = float(mild["value"]) * 0.95
    p.write_text(json.dumps(mild))
    assert check_perf.main(["--candidate", str(p)]) == 0


def test_check_perf_json_and_error_contracts(tmp_path, capsys):
    p = tmp_path / "garbage.json"
    p.write_text(json.dumps({"no": "record"}))
    assert check_perf.main(["--candidate", str(p)]) == 2

    # setup failures are exit 2 ("comparison impossible"), never 1: a CI
    # consumer must not read a typo'd path as a perf regression
    assert check_perf.main(
        ["--candidate", str(tmp_path / "no-such-file.json")]) == 2
    badw = tmp_path / "badw.json"
    badw.write_text(json.dumps(
        {"waivers": [{"metric": "m", "justification": ""}]}))
    assert check_perf.main(["--waivers", str(badw)]) == 2

    assert check_perf.main(["--json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] is True and verdict["checked"] > 0

    # --strict-waivers: a waiver matching nothing fails the run
    stale = tmp_path / "waivers.json"
    stale.write_text(json.dumps({"waivers": [
        {"metric": "no.such.teff", "justification": "left over"}
    ]}))
    assert check_perf.main(["--waivers", str(stale)]) == 0
    assert check_perf.main(["--waivers", str(stale),
                            "--strict-waivers"]) == 1


def test_stale_waivers_tracked_per_entry_not_per_metric():
    """Two same-metric waivers where only one can fire: staleness must be
    keyed on the ENTRY that matched, or the dead round-scoped twin hides
    behind its sibling forever."""
    cand, ref = {"a.teff": 50.0}, {"a.teff": 100.0}
    live = {"metric": "a.teff", "justification": "covers round 9",
            "rounds": [9]}
    dead = {"metric": "a.teff", "justification": "covered round 3 only",
            "rounds": [3]}
    cmp = perf.compare_metrics(cand, ref, waivers=[dead, live],
                               candidate_round=9)
    assert len(cmp["waived"]) == 1
    assert cmp["waived"][0]["waiver_index"] == 1  # the live entry, by id
    used = {w["waiver_index"] for w in cmp["waived"]}
    stale = [w for i, w in enumerate([dead, live]) if i not in used]
    assert stale == [dead]


# -- refresh_cost_baseline CLI (the audit contract) ---------------------------


@pytest.fixture()
def _stub_census(monkeypatch):
    """Route the refresh script's census through a stub (the REAL census
    compiles the whole matrix — that run lives in the tier-1 full suite)."""
    from implicitglobalgrid_tpu.analysis import costmodel

    census = {"prog": {"flops": 1000, "kernel_launches": 3}}
    monkeypatch.setattr(costmodel, "cost_census", lambda ctx: census)
    monkeypatch.setattr(refresh_cost_baseline, "_ensure_devices",
                        lambda: None)
    return census


def test_refresh_requires_a_justify_note_per_changed_metric(
        tmp_path, _stub_census, capsys):
    out = tmp_path / "cost_baseline.json"

    # every metric is new -> every one needs a note
    assert refresh_cost_baseline.main(["--out", str(out)]) == 1
    assert "without a --justify note" in capsys.readouterr().err
    assert not out.exists()

    # a catch-all covers them; the file passes the loader's audit check
    assert refresh_cost_baseline.main(
        ["--out", str(out), "--justify-all", "initial pin"]
    ) == 0
    from implicitglobalgrid_tpu.analysis import costmodel

    data = costmodel.load_baseline(str(out))
    assert data["programs"]["prog"]["metrics"] == _stub_census["prog"]
    assert data["programs"]["prog"]["justifications"]["flops"] == (
        "initial pin"
    )

    # unchanged census: nothing to refresh, notes survive
    assert refresh_cost_baseline.main(["--out", str(out)]) == 0
    assert "nothing to refresh" in capsys.readouterr().out


def test_refresh_per_metric_note_wins_and_dry_run_writes_nothing(
        tmp_path, _stub_census, capsys):
    out = tmp_path / "cost_baseline.json"
    assert refresh_cost_baseline.main(
        ["--out", str(out), "--justify-all", "initial pin"]
    ) == 0

    _stub_census["prog"]["flops"] = 2000  # a real change

    assert refresh_cost_baseline.main(["--out", str(out), "--dry-run"]) == 0
    assert "prog::flops: 1000 -> 2000" in capsys.readouterr().out
    from implicitglobalgrid_tpu.analysis import costmodel

    assert costmodel.load_baseline(str(out))["programs"]["prog"][
        "metrics"]["flops"] == 1000  # dry run wrote nothing

    assert refresh_cost_baseline.main(["--out", str(out)]) == 1  # no note
    assert refresh_cost_baseline.main([
        "--out", str(out),
        "--justify", "prog::flops=PR 8 fuses the halo pack (bench +12%)",
    ]) == 0
    data = costmodel.load_baseline(str(out))
    assert data["programs"]["prog"]["metrics"]["flops"] == 2000
    assert "PR 8 fuses" in data["programs"]["prog"]["justifications"]["flops"]
    # the unchanged metric keeps its original note
    assert data["programs"]["prog"]["justifications"]["kernel_launches"] == (
        "initial pin"
    )

    with pytest.raises(SystemExit):
        refresh_cost_baseline.main(["--justify", "malformed-no-separator"])


def test_refresh_audits_vanished_metrics_too(tmp_path, _stub_census, capsys):
    """A baselined metric the census stopped producing is the gate LOSING
    a check — dropping it from the baseline needs the same --justify audit
    as changing it, and --dry-run must say so (not 'nothing to refresh')."""
    out = tmp_path / "cost_baseline.json"
    assert refresh_cost_baseline.main(
        ["--out", str(out), "--justify-all", "initial pin"]
    ) == 0

    del _stub_census["prog"]["kernel_launches"]

    assert refresh_cost_baseline.main(["--out", str(out), "--dry-run"]) == 0
    assert "prog::kernel_launches: 3 -> <removed>" in capsys.readouterr().out
    assert refresh_cost_baseline.main(["--out", str(out)]) == 1  # no note
    assert refresh_cost_baseline.main([
        "--out", str(out),
        "--justify", "prog::kernel_launches=toolchain stopped exposing it",
    ]) == 0
    from implicitglobalgrid_tpu.analysis import costmodel

    assert "kernel_launches" not in costmodel.load_baseline(
        str(out))["programs"]["prog"]["metrics"]

    # a WHOLE program leaving the matrix is audited the same way
    _stub_census.clear()
    assert refresh_cost_baseline.main(["--out", str(out)]) == 1
    assert "prog::*" in capsys.readouterr().err
    assert refresh_cost_baseline.main(
        ["--out", str(out), "--justify", "prog::*=config retired in PR 9"]
    ) == 0
    assert costmodel.load_baseline(str(out))["programs"] == {}
