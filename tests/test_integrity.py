"""Silent-data-corruption integrity plane (ISSUE 18; docs/robustness.md).

Pins the three detectors and their contracts:

* transport checksums — per-hop XOR-fold words on the coalesced packed
  ``ppermute`` payload: clean exchanges bit-exact with zero false
  positives, an armed in-flight flip trips the RECEIVER with an
  `IntegrityError` implicating the SENDER, the flip is consumed (the
  clean cached program survives), and the integrity programs live in a
  SEPARATE jit cache so the plain path's cache keys (pinned by
  ``test_coalesced_halo``) and the ``IGG_INTEGRITY=0`` zero-overhead pin
  stay intact;
* shadow-step audit — the interpret-mode bit-compare matrix: healthy
  re-execution is bit-identical across all three models x pipelined
  on/off (zero false positives at ``IGG_INTEGRITY_EVERY=1``), and an
  injected post-step ``bit_flip`` is caught at the cadence with the
  corrupting rank implicated;
* lineage digests — a checkpoint whose bytes were flipped AFTER the
  digests were taken (the ``bit_flip:…:ckpt`` placement) passes CRC but
  fails lineage ("corrupt when saved"), and `latest_checkpoint` walks
  past the poisoned generation; the streaming verifier stays
  chunk-bounded in memory (the RSS satellite).

Plus the escalation path (classify -> policy -> fleet), the
``bit_flip`` spec grammar (pointed rejections — the fault-matrix
satellite), and the rank-uniformity census of `integrity.plan`.
"""

import json
import os
import tracemalloc
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu import integrity
from implicitglobalgrid_tpu.integrity import IntegrityError
from implicitglobalgrid_tpu.models import diffusion3d
from implicitglobalgrid_tpu.ops import halo as halo_mod
from implicitglobalgrid_tpu.utils import checkpoint as ck
from implicitglobalgrid_tpu.utils import resilience
from implicitglobalgrid_tpu.utils import telemetry as tele
from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret
from implicitglobalgrid_tpu.utils.resilience import (
    FaultInjector,
    RunGuard,
    guarded_time_loop,
)


def _counter(name: str) -> int:
    return tele.snapshot()["counters"].get(name, 0)


# --- transport checksum primitives ------------------------------------------


def test_fold_words_xor_round_trip():
    """`append_checksum`/`split_and_verify` round-trip the exact payload
    with a clean verdict; any single flipped bit — payload OR checksum
    word — trips the recomputed fold."""
    words = jnp.asarray(
        np.random.default_rng(0).integers(0, 2**32, 37, dtype=np.uint32)
    )
    wire = integrity.append_checksum(words)
    assert wire.shape == (38,)
    payload, bad = integrity.split_and_verify(wire)
    assert np.array_equal(np.asarray(payload), np.asarray(words))
    assert not bool(np.asarray(bad))
    for pos in (0, 17, 37):  # payload head, middle, the checksum word
        flipped = wire.at[pos].set(wire[pos] ^ 1)
        _, bad = integrity.split_and_verify(flipped)
        assert bool(np.asarray(bad)), f"flip at word {pos} not caught"
    # the degenerate hop: an empty payload folds to the zero word
    assert int(integrity.fold_words(words[:0])) == 0


def test_checksum_covers_nan_and_negative_zero_bits():
    """The fold runs over the unsigned word view, so byte patterns a float
    compare can never distinguish (-0.0 vs +0.0, NaN payload bits) still
    change the checksum."""
    a = jnp.asarray(np.array([np.nan, -0.0, 1.0]).view(np.uint64))
    b = jnp.asarray(np.array([np.nan, +0.0, 1.0]).view(np.uint64))
    assert int(integrity.fold_words(a)) != int(integrity.fold_words(b))


# --- transport checksums in the exchange ------------------------------------


def _grid_and_fields():
    igg.init_global_grid(12, 12, 12, periodx=1, periody=1, quiet=True)
    T = igg.zeros((12, 12, 12)) + 1.5
    C = igg.ones((12, 12, 12))
    return T, C


def test_transport_checksum_clean_exchange_no_false_positive(monkeypatch):
    monkeypatch.setenv("IGG_INTEGRITY", "1")
    T, C = _grid_and_fields()
    want_T, want_C = igg.update_halo(T + 0, C + 0)
    # a second exchange on already-consistent fields is a bitwise no-op
    oT, oC = igg.update_halo(want_T + 0, want_C + 0)
    assert np.array_equal(np.asarray(oT), np.asarray(want_T))
    assert np.array_equal(np.asarray(oC), np.asarray(want_C))
    # checksummed programs live in their own cache: the plain cache keys
    # (pinned by test_coalesced_halo) must not grow integrity entries
    assert halo_mod._integrity_jit_cache
    assert all(len(k) == 6 for k in halo_mod._integrity_jit_cache)


def test_transport_checksum_trips_receiver_and_implicates_sender(monkeypatch):
    monkeypatch.setenv("IGG_INTEGRITY", "1")
    T, C = _grid_and_fields()
    base = _counter("integrity.transport_mismatches")
    halo_mod.arm_transport_flip(3)
    with pytest.raises(IntegrityError) as ei:
        igg.update_halo(T + 0, C + 0)
    err = ei.value
    assert err.detector == "transport_checksum"
    assert err.implicated_rank == 3  # the flipping SENDER, named by a peer
    assert err.dim in (0, 1, 2)
    assert err.fields  # the hop's field labels ride the error
    assert _counter("integrity.transport_mismatches") >= base + 1
    # the flip was CONSUMED (it is part of the program cache key): the
    # next exchange runs the clean cached program and must not trip
    oT, oC = igg.update_halo(T + 0, C + 0)
    assert np.array_equal(np.asarray(oT), np.asarray(T))
    assert np.array_equal(np.asarray(oC), np.asarray(C))


def test_transport_checksum_single_field_routes_packed(monkeypatch):
    """Single-field exchanges (normally the unpacked singleton group) must
    also carry the checksum word — the wire form covers every hop."""
    monkeypatch.setenv("IGG_INTEGRITY", "1")
    T, _ = _grid_and_fields()
    out = igg.update_halo(T + 0)
    assert np.array_equal(np.asarray(out), np.asarray(T))
    halo_mod.arm_transport_flip(0)
    with pytest.raises(IntegrityError):
        igg.update_halo(T + 0)


def test_integrity_off_is_zero_overhead(monkeypatch):
    """``IGG_INTEGRITY=0`` pins everything off — like ``IGG_TELEMETRY=0``:
    no checksummed programs compiled, the audit cadence forced to 0 even
    when ``IGG_INTEGRITY_EVERY`` is set."""
    monkeypatch.setenv("IGG_INTEGRITY", "0")
    monkeypatch.setenv("IGG_INTEGRITY_EVERY", "3")
    halo_mod._integrity_jit_cache.clear()
    T, C = _grid_and_fields()
    igg.update_halo(T + 0, C + 0)
    assert not halo_mod._integrity_jit_cache
    guard = RunGuard()
    assert guard.integrity_every == 0
    assert not guard.enabled


def test_integrity_unset_honors_audit_cadence(monkeypatch):
    """Unset ``IGG_INTEGRITY`` leaves transport checksums off but honors
    the ``IGG_INTEGRITY_EVERY`` audit cadence (the tri-state contract)."""
    monkeypatch.delenv("IGG_INTEGRITY", raising=False)
    monkeypatch.setenv("IGG_INTEGRITY_EVERY", "2")
    halo_mod._integrity_jit_cache.clear()
    T, C = _grid_and_fields()
    igg.update_halo(T + 0, C + 0)
    assert not halo_mod._integrity_jit_cache  # checksums not armed
    guard = RunGuard()
    assert guard.integrity_every == 2
    assert guard.enabled


# --- shadow-step audit -------------------------------------------------------


_MATRIX = [
    ("diffusion3d", ("T", "Cp"), {}),
    ("acoustic3d", ("P", "Vx", "Vy", "Vz"), dict(periodz=1)),
    ("porous_convection3d", ("T", "Pf", "qDx", "qDy", "qDz"),
     dict(periodz=1, npt=5)),
]


@pytest.mark.parametrize("name,names,extra", _MATRIX,
                         ids=[m[0] for m in _MATRIX])
@pytest.mark.parametrize("pipelined", [False, True],
                         ids=["serialized", "pipelined"])
def test_shadow_audit_healthy_bit_identical(name, names, extra, pipelined):
    """The interpret-mode matrix: at ``integrity_every=1`` every committed
    step is re-executed and bit-compared — healthy runs must re-execute
    bit-identically (zero false positives) for all three models under
    both the serialized and the boundary-first pipelined cadence."""
    from implicitglobalgrid_tpu import models

    model = getattr(models, name)
    setup_extra = dict(extra)
    npt = setup_extra.pop("npt", None)
    kw = dict(devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
              overlapx=4, overlapy=4, overlapz=4, quiet=True,
              dtype=jnp.float32, **setup_extra)
    if npt is not None:
        kw["npt"] = npt
    state, params = model.setup(24, 32, 64, **kw)
    base = _counter("integrity.audits")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with pallas_force_interpret():
            step = model.make_multi_step(
                params, 2, donate=False, fused_k=2, fused_tile=(8, 16),
                pipelined=pipelined,
            )
            guard = RunGuard(integrity_every=1, names=names)
            assert guard.enabled
            state = guarded_time_loop(
                step, state, 1, guard=guard, sync_every_step=True,
            )
    jax.block_until_ready(state)
    assert _counter("integrity.audits") == base + 1


def test_shadow_audit_catches_state_bit_flip(fault_injection):
    """One flipped mantissa bit in the committed post-step state — finite,
    invisible to the NaN/Inf guard — trips the audit at the cadence with
    ``detector=shadow_audit``."""
    state, params = diffusion3d.setup(12, 12, 12, quiet=True)
    fault_injection("bit_flip:step2:T")
    step = diffusion3d.make_step(params, donate=False)
    guard = RunGuard(integrity_every=1, names=("T", "Cp"))
    base = _counter("integrity.audit_mismatches")
    with pytest.raises(IntegrityError) as ei:
        guarded_time_loop(step, state, 4, guard=guard, sync_every_step=True)
    assert ei.value.detector == "shadow_audit"
    assert ei.value.step == 2
    assert ei.value.implicated_rank is not None
    assert _counter("integrity.audit_mismatches") == base + 1


def test_shadow_audit_guard_invisible_without_integrity(fault_injection):
    """The same ``bit_flip`` with the integrity plane OFF sails through the
    NaN/Inf guard — the exact gap the plane exists to close (and why
    ``bit_flip`` is opt-in, never part of the default chaos draw)."""
    state, params = diffusion3d.setup(12, 12, 12, quiet=True)
    fault_injection("bit_flip:step2:T")
    step = diffusion3d.make_step(params, donate=False)
    guard = RunGuard(guard_every=1, policy="raise", names=("T", "Cp"))
    assert guard.integrity_every == 0
    out = guarded_time_loop(
        step, state, 3, guard=guard, sync_every_step=True
    )
    assert np.all(np.isfinite(np.asarray(out[0])))  # corrupt but finite


def test_serving_pool_audits_sampled_member(monkeypatch):
    """A batched pool audits one round-robin-sampled member per audited
    round through the SAME compiled multi-step; healthy rounds pass."""
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    monkeypatch.setenv("IGG_INTEGRITY_EVERY", "1")
    state, params = diffusion3d.setup(12, 12, 12, quiet=True)
    loop = ServingLoop(diffusion3d, params, capacity=1, steps_per_round=1)
    assert loop.integrity_every == 1
    loop.submit(Request(state=state, max_steps=2, tenant="t0"))
    base = _counter("integrity.audits")
    results = loop.run(max_rounds=4)
    assert len(results) == 1
    assert _counter("integrity.audits") >= base + 2


# --- bit_flip fault grammar (fault-matrix satellite) -------------------------


def test_bit_flip_spec_round_trips():
    inj = FaultInjector.from_spec("bit_flip:step3:T:proc2")
    assert inj.kind == "bit_flip" and inj.step == 3
    assert inj.field == "T" and inj.target == 2
    assert inj.spec() == "bit_flip:step3:T:proc2"
    inj = FaultInjector.from_spec("bit_flip:step4:transport")
    assert inj.field == "transport" and inj.target is None
    inj = FaultInjector.from_spec("bit_flip:step5:ckpt:proc1")
    assert inj.field == "ckpt" and inj.target == 1
    assert FaultInjector.from_spec("bit_flip:step6").field is None


def test_bit_flip_spec_rejects_bare_integer_component():
    with pytest.raises(ValueError, match="bare integer"):
        FaultInjector.from_spec("bit_flip:step3:2")


def test_bit_flip_rejects_nonexistent_field():
    """A spec naming a field the run does not have must fail POINTEDLY at
    fire time, listing the run's actual fields."""
    state, params = diffusion3d.setup(12, 12, 12, quiet=True)
    inj = FaultInjector.from_spec("bit_flip:step1:Temperature")
    with pytest.raises(ValueError) as ei:
        inj.maybe_bit_flip(tuple(state), 1, names=("T", "Cp"))
    msg = str(ei.value)
    assert "Temperature" in msg and "T" in msg and "Cp" in msg


def test_bit_flip_not_in_default_chaos_kinds():
    """Guard-invisible by design: a default chaos storm drawing bit_flip
    without the integrity plane armed would silently falsify results."""
    assert "bit_flip" in resilience.FAULT_KINDS
    assert "bit_flip" not in resilience.CHAOS_KINDS


def test_halo_corrupt_documented_as_guard_visible_twin():
    """The fault matrix names ``halo_corrupt`` the guard-VISIBLE twin of
    ``bit_flip`` (NaN payload vs finite flip) — pinned in the injector
    docstring so the matrix and the code cannot drift."""
    doc = FaultInjector.__doc__
    assert "bit_flip" in doc and "halo_corrupt" in doc
    assert "guard" in doc.lower()


# --- lineage digests ---------------------------------------------------------


def test_lineage_chains_and_detects_poisoned_generation(
    tmp_path, fault_injection
):
    igg.init_global_grid(12, 12, 12, quiet=True)
    T = igg.zeros((12, 12, 12)) + 1.5
    C = igg.ones((12, 12, 12))
    d = str(tmp_path / "ck")

    p4 = ck.save_checkpoint(d, (T, C), 4)
    assert ck.verify_checkpoint(p4) is None
    lin4 = ck.checkpoint_meta(p4)["lineage"]
    assert len(lin4["fields"]) == 2 and lin4["prev_step"] is None
    assert all(f["digest"] and f["chain"] for f in lin4["fields"])

    p6 = ck.save_checkpoint(d, (T, C), 6)
    lin6 = ck.checkpoint_meta(p6)["lineage"]
    assert lin6["prev_step"] == 4
    # same state -> same digest; the CHAIN still rolls forward
    assert lin6["fields"][0]["digest"] == lin4["fields"][0]["digest"]
    assert lin6["fields"][0]["chain"] != lin4["fields"][0]["chain"]

    # ckpt-placement flip: digests taken from the live arrays, bytes
    # flipped before the writer -> CRC passes, lineage convicts
    fault_injection("bit_flip:step8:ckpt")
    p8 = ck.save_checkpoint(d, (T, C), 8)
    problem = ck.verify_checkpoint(p8)
    assert problem is not None
    assert "already corrupt when saved" in problem

    # the fallback walks PAST the poisoned generation
    best = ck.latest_checkpoint(d)
    assert best is not None and best.endswith("step_00000006")
    with pytest.raises(ValueError, match="already corrupt"):
        ck.restore_checkpoint(p8)
    state, step, _ = ck.restore_checkpoint(best)
    assert step == 6
    assert np.array_equal(np.asarray(state[0]), np.asarray(T))


def test_lineage_ignores_legacy_meta(tmp_path):
    """Generations saved before the lineage section verify clean (the
    format stays readable both ways)."""
    igg.init_global_grid(12, 12, 12, quiet=True)
    T = igg.ones((12, 12, 12))
    d = str(tmp_path / "ck")
    p = ck.save_checkpoint(d, (T,), 1)
    meta_path = os.path.join(p, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["lineage"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    assert ck.verify_checkpoint(p) is None


def test_streaming_verifier_memory_bounded(tmp_path):
    """The integrity sweep must not spike RSS: digesting a shard streams
    `STREAM_CHUNK` slices, never a whole member (the ``rss_growth``
    anomaly rule must not fire on our own verifier)."""
    from implicitglobalgrid_tpu.integrity import lineage

    big = np.random.default_rng(0).random((4, 1 << 20))  # 32 MiB payload
    path = str(tmp_path / "shards_p0.npz")
    np.savez(path, f0_o0_0_0=big.view(np.uint8).reshape(-1),
             f0_o0_0_0_shape=np.asarray(big.shape))
    del big
    tracemalloc.start()
    digests = lineage.stream_npz_block_digests(path)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert set(digests) == {"f0_o0_0_0"}
    assert peak < 8 * lineage.STREAM_CHUNK, (
        f"streaming verifier peaked at {peak} bytes"
    )
    # and the streamed digest equals the in-memory one
    raw = np.load(path)["f0_o0_0_0"]
    assert digests["f0_o0_0_0"] == lineage.block_digest(raw)


# --- escalation: classify -> policy -> fleet ---------------------------------


def test_sdc_bundle_classifies_and_implicates_sender():
    from implicitglobalgrid_tpu.supervisor.classify import classify

    ev = {
        "bundles": {1: [{"reason": "sdc",
                         "info": {"detector": "transport_checksum",
                                  "implicated_rank": 0}}]},
        "alerts": [], "events": [],
    }
    inc = classify((1, 1), ev)
    assert inc.kind == "silent_corruption"
    assert inc.ranks == (0,)  # the SENDER, not the detecting rank
    assert inc.detail["bundle_rank"] == 1
    assert inc.detail["detector"] == "transport_checksum"


def test_sdc_policy_quarantines_on_first_strike():
    from implicitglobalgrid_tpu.supervisor.classify import Incident
    from implicitglobalgrid_tpu.supervisor.policy import (
        RecoveryPolicy,
        SupervisorState,
        decide,
    )

    inc = Incident(kind="silent_corruption", ranks=(2,), rcs=(0, 0, 1),
                   detail={"detector": "shadow_audit"})
    state = SupervisorState()
    state.record_incident(inc)
    d = decide(inc, state, RecoveryPolicy(), ladder_len=3)
    assert d.action == "quarantine"  # no strike accrual for a liar
    assert d.quarantined == (2,) and d.rung == 1
    d = decide(inc, SupervisorState(rung=2), RecoveryPolicy(), ladder_len=3)
    assert d.action == "give_up" and d.quarantined == (2,)


def test_sdc_pool_quarantined_not_respawned():
    from implicitglobalgrid_tpu.fleet.policy import (
        FleetPolicy,
        FleetState,
        decide_pool,
    )
    from implicitglobalgrid_tpu.supervisor.classify import Incident

    inc = Incident(kind="sdc", ranks=(3,), rcs=(None,),
                   detail={"pool": "p0", "devices": "tpu:0-3",
                           "detector": "shadow_audit"})
    d = decide_pool(inc, FleetState(), FleetPolicy())
    assert d.action == "quarantine"
    assert d.quarantined == ("tpu:0-3",)
    assert "respawn" in d.reason  # the reason explains why not respawn


# --- rank-uniformity census --------------------------------------------------


def test_integrity_plan_census_rank_uniform():
    from implicitglobalgrid_tpu.analysis.collectives import (
        check_rank_consistency,
        integrity_plan_censuses,
    )

    censuses = list(integrity_plan_censuses(None))
    assert censuses
    for census in censuses:
        assert check_rank_consistency(census) == []


def test_integrity_plan_checksums_add_no_collective():
    from implicitglobalgrid_tpu.integrity.plan import integrity_plan

    plain = integrity_plan(True, checksums=False, audit_every=0, step=5,
                           exchange_dims=3)
    summed = integrity_plan(True, checksums=True, audit_every=0, step=5,
                            exchange_dims=3)
    assert len(plain) == len(summed) == 3  # payload-only delta, same hops
    audited = integrity_plan(True, checksums=True, audit_every=5, step=5,
                             exchange_dims=3)
    assert len(audited) == 4  # exactly one cadence-keyed psum
    assert audited[-1] == ("psum", "audit-compare")
    off_cadence = integrity_plan(True, checksums=True, audit_every=5,
                                 step=6, exchange_dims=3)
    assert len(off_cadence) == 3
