"""Tier-1 knob lint (`scripts/check_knobs.py`, docs/observability.md).

Every ``IGG_*`` env var referenced anywhere in the package must be declared
in `utils/config.py` and documented in `docs/usage.md` — an undocumented
knob fails the suite, so the configuration tier cannot silently grow
invisible switches (how ``IGG_GATHER_BATCH`` went undocumented for two
rounds).
"""

import importlib.util
import os

_here = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "igg_check_knobs",
    os.path.join(os.path.dirname(_here), "scripts", "check_knobs.py"),
)
check_knobs = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_knobs)


def test_every_referenced_knob_is_declared_and_documented():
    probs = check_knobs.violations()
    assert not probs, "undeclared/undocumented IGG_* knob(s):\n" + "\n".join(
        f"  - {p}" for p in probs
    )


def test_lint_sees_the_known_knobs():
    """The scanner itself must be alive: the long-standing knobs have to be
    in its reference census (an empty scan passing would be a broken lint,
    not a clean tree)."""
    refs = check_knobs.referenced_knobs()
    for knob in (
        "IGG_DONATE",
        "IGG_FAULT_INJECT",
        "IGG_GATHER_BATCH",
        "IGG_TELEMETRY",
        "IGG_TELEMETRY_DIR",
        "IGG_HEARTBEAT_EVERY",
        "IGG_VMEM_MB",
        # the serving front-door tier (ISSUE 12, docs/serving.md): these
        # must stay in the census so an undocumented successor still fails
        "IGG_SERVE_PORT",
        "IGG_TENANT_QUOTA",
        "IGG_FRONTDOOR_QUEUE_MAX",
        "IGG_AUTOSCALE_SUSTAIN",
        # the fleet tier (ISSUE 16, docs/serving.md "The fleet tier")
        "IGG_FLEET_RESPAWN_LIMIT",
        "IGG_FLEET_CANARY_P99_S",
        "IGG_RESULT_KEEP",
    ):
        assert knob in refs, f"{knob} vanished from the package scan"


def test_lint_reports_an_undeclared_knob(tmp_path, monkeypatch):
    """Negative control: a package file referencing a brand-new knob must
    trip both the declaration and the documentation check."""
    pkg = tmp_path / "implicitglobalgrid_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "utils" / "config.py").write_text('"""IGG_DECLARED_ONLY"""\n')
    (pkg / "rogue.py").write_text(
        'import os\nos.environ.get("IGG_BRAND_NEW_KNOB")\n'
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "usage.md").write_text("| `IGG_DECLARED_ONLY` | - | x |\n")
    monkeypatch.setattr(check_knobs, "REPO", str(tmp_path))
    monkeypatch.setattr(check_knobs, "PACKAGE", str(pkg))
    monkeypatch.setattr(check_knobs, "CONFIG", str(pkg / "utils" / "config.py"))
    monkeypatch.setattr(check_knobs, "USAGE", str(docs / "usage.md"))
    probs = check_knobs.violations()
    assert len(probs) == 2
    assert all("IGG_BRAND_NEW_KNOB" in p for p in probs)
