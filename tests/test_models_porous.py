"""Porous-convection model tests (pseudo-transient Darcy + temperature)."""

import numpy as np
import pytest

import jax

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import porous_convection3d as pc

from tests.test_models_diffusion import dedup_global


def _run(nt, nx, devices=None, npt=8, hide_comm=False):
    state, params = pc.setup(nx, nx, nx, devices=devices, npt=npt, hide_comm=hide_comm)
    gg = igg.get_global_grid()
    dims = gg.dims
    step = pc.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    out = {}
    for name, A in zip(("T", "Pf", "qDx", "qDy", "qDz"), state):
        shp = igg.local_shape(A)
        ol = tuple(igg.ol(d, A) for d in range(3))
        g = np.asarray(igg.gather(A))
        out[name] = dedup_global(g, dims, shp, ol) if max(dims) > 1 else g
    igg.finalize_global_grid()
    return out


def test_multi_matches_single():
    nt, nx = 4, 10
    multi = _run(nt, nx)  # 2x2x2, global 18^3
    single = _run(nt, 18, devices=[jax.devices()[0]])
    for k in multi:
        np.testing.assert_allclose(multi[k], single[k], rtol=1e-11, atol=1e-12, err_msg=k)


def _pt_relax(params, n, state):
    """Run ``n`` PT Darcy iterations at frozen T; return (Pf, qDx, qDy, qDz)."""
    from jax import lax

    it = pc._pt_iteration(params)
    T, Pf, qDx, qDy, qDz = state
    f = jax.jit(
        lambda T, Pf, qx, qy, qz: lax.fori_loop(
            0, n, lambda i, s: it(T, *s), (Pf, qx, qy, qz)
        )
    )
    return f(T, Pf, qDx, qDy, qDz)


def _div_residual(params, pt_state):
    Pf, qDx, qDy, qDz = pt_state
    div = (
        np.diff(np.asarray(qDx), axis=0) / params.dx
        + np.diff(np.asarray(qDy), axis=1) / params.dy
        + np.diff(np.asarray(qDz), axis=2) / params.dz
    )
    return float(np.max(np.abs(div)))


def test_hide_comm_matches_plain():
    # Overlapped flux exchange (the acoustic pattern applied to the PT inner
    # loop) must be bit-equivalent to the plain per-iteration exchange.
    plain = _run(3, 10)
    hide = _run(3, 10, hide_comm=True)
    for k in plain:
        np.testing.assert_allclose(hide[k], plain[k], rtol=1e-12, atol=1e-12)


def test_pt_solver_converges_and_bound_is_sharp():
    """The hand-derived PT relaxation bounds must be pinned by convergence.

    The Darcy continuity residual max|div(qD)| must contract by a pinned
    factor over the PT iterations (beta_p's von Neumann bound,
    `porous_convection3d.setup`), and violating the bound (beta_p scaled 3x,
    so beta*theta*k^2 > 2) must blow the residual up — a wrong bound cannot
    slip through as "just slow convergence".
    """
    import dataclasses

    state, params = pc.setup(16, 16, 16, devices=[jax.devices()[0]], quiet=True)
    try:
        r_early = _div_residual(params, _pt_relax(params, 2, state))
        r_late = _div_residual(params, _pt_relax(params, 160, state))
        assert r_early > 1.0  # buoyancy drives a nontrivial residual first
        # measured 6.1e-3 vs 4.45 => contraction ~730x; pin with margin
        assert r_late < 0.02
        assert r_late < r_early / 100.0
        bad = dataclasses.replace(params, beta_p=params.beta_p * 3.0)
        r_bad = _div_residual(bad, _pt_relax(bad, 40, state))
        assert not np.isfinite(r_bad) or r_bad > 1e6  # diverges, not "slow"
    finally:
        igg.finalize_global_grid()


def test_multi_step_matches_single_steps():
    """The production chunk path (nsteps per XLA program) must reproduce the
    per-step path exactly."""
    nx, nt = 10, 3
    state, params = pc.setup(nx, nx, nx, npt=6)
    step = pc.make_step(params, donate=False)
    multi = pc.make_multi_step(params, nt, donate=False)
    s1 = state
    for _ in range(nt):
        s1 = jax.block_until_ready(step(*s1))
    s3 = jax.block_until_ready(multi(*state))
    for a, b, name in zip(s1, s3, ("T", "Pf", "qDx", "qDy", "qDz")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-12, atol=1e-13, err_msg=name
        )
    igg.finalize_global_grid()


def test_pt_cadence_matches_per_iteration():
    """Deep-halo PT cadence: w relaxation iterations + one width-w 4-field
    slab exchange must be bit-identical to the per-iteration Pf exchange at
    group boundaries (owned cells)."""
    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True, npt=6)
    nx, nt = 12, 2

    def _run_cadence(exchange_every):
        state, params = pc.setup(nx, nx, nx, **kw)
        gg = igg.get_global_grid()
        dims, o = gg.dims, gg.overlaps
        step = pc.make_multi_step(params, nt, donate=False, exchange_every=exchange_every)
        state = jax.block_until_ready(step(*state))
        out = {}
        for name, A in zip(("T", "Pf", "qDx", "qDy", "qDz"), state):
            shp = igg.local_shape(A)
            ol = tuple(igg.ol(d, A) for d in range(3))
            g = np.asarray(igg.gather(A))
            out[name] = dedup_global(g, dims, shp, ol) if max(dims) > 1 else g
        igg.finalize_global_grid()
        return out

    ref = _run_cadence(1)
    cad = _run_cadence(2)
    for k in ref:
        np.testing.assert_array_equal(cad[k], ref[k], err_msg=k)


def test_pt_cadence_validation():
    state, params = pc.setup(10, 10, 10, npt=6, quiet=True)  # overlap 2
    with pytest.raises(ValueError, match="deep halo"):
        pc.make_multi_step(params, 2, exchange_every=2)
    igg.finalize_global_grid()


def test_pt_schedule():
    """The ragged-npt chunking (round 4, VERDICT r3 #5: ``w | npt`` made the
    kernel benefit depend on a numerics parameter)."""
    from implicitglobalgrid_tpu.models.porous_convection3d import _pt_schedule

    assert _pt_schedule(12, 6) == (0, [6, 6])
    assert _pt_schedule(10, 6) == (0, [6, 4])
    assert _pt_schedule(8, 6) == (0, [6, 2])
    assert _pt_schedule(9, 6) == (1, [6, 2])
    assert _pt_schedule(10, 2) == (0, [2] * 5)
    assert _pt_schedule(1, 2) == (1, [])
    assert _pt_schedule(5, 4) == (1, [4])
    # w=1 admits no even kernel chunk — everything leads (regression: this
    # case used to loop forever).
    assert _pt_schedule(10, 1) == (10, [])
    # The pure-XLA exchange_every cadence has no parity constraint: odd w
    # keeps the user's requested group size (regression: even-rounding was
    # wrongly applied, inflating the collective count by ~50%).
    assert _pt_schedule(6, 3, even=False) == (0, [3, 3])
    assert _pt_schedule(7, 3, even=False) == (0, [3, 3, 1])


def test_ragged_cadence_matches_per_iteration():
    """exchange_every with npt % w != 0 (ragged schedule) must still match
    the per-iteration path at time-step boundaries."""
    kw = dict(overlapx=8, overlapy=8, overlapz=8, npt=5, quiet=True)
    state, params = pc.setup(18, 18, 18, **kw)
    step = pc.make_multi_step(params, 2, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(18, 18, 18, **kw)
    step = pc.make_multi_step(params, 2, donate=False, exchange_every=4)
    cad = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()
    # Not bitwise: the lead iteration changes fusion boundaries, so the
    # compiler contracts FMAs differently (f64 ULPs).
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), cad, ref):
        np.testing.assert_allclose(g, r, rtol=1e-13, atol=1e-13, err_msg=name)


def test_convection_starts_and_is_bounded():
    state, params = pc.setup(12, 12, 12, npt=8)
    step = pc.make_step(params)
    for _ in range(12):
        state = jax.block_until_ready(step(*state))
    T = np.asarray(igg.gather(pc.temperature(state)))
    qDz = np.asarray(igg.gather(state[4]))
    igg.finalize_global_grid()
    assert np.isfinite(T).all() and np.isfinite(qDz).all()
    # Dirichlet walls intact (frozen boundary planes):
    assert abs(T[:, :, 0].mean() - 0.5) < 0.1
    assert abs(T[:, :, -1].mean() + 0.5) < 0.1
    # buoyancy must have driven an upward Darcy flux somewhere
    assert qDz.max() > 1e-8
    # temperature stays within the physical contrast (+ perturbation margin)
    assert T.max() <= 0.65 and T.min() >= -0.65


def test_fused_single_device_matches_xla():
    """fused_k on a no-halo-activity grid: the fluxes stay in the kernel's
    padded layout across the whole PT loop; results must match the plain
    multi-step path to few (scale-relative) f32 ULPs."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 2
    # dtype pinned: f64 is outside the kernel envelope (see the acoustic
    # fused tests); without it this exercises the fallback, not the kernel.
    kw = dict(devices=jax.devices()[:1], npt=4, quiet=True,
              dtype=jax.numpy.float32)
    state, params = pc.setup(16, 32, 128, **kw)
    step = pc.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(A) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = pc.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(A) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)


@pytest.mark.parametrize("npt,fused_k", [(10, 4), (5, 2)])
def test_fused_ragged_npt_matches_xla(npt, fused_k):
    """npt % fused_k != 0 (round 4, VERDICT r3 #5): the ragged schedule —
    odd lead iteration + even kernel chunks, all exchanges at width w —
    must match the per-iteration path.  (10, 4) -> chunks [4, 4, 2];
    (5, 2) -> lead 1 + chunks [2, 2]."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 2
    kw = dict(
        devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
        overlapx=2 * fused_k, npt=npt, quiet=True, dtype=jax.numpy.float32,
    )
    state, params = pc.setup(16, 32, 128, **kw)
    step = pc.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = pc.make_multi_step(
            params, nt, donate=False, fused_k=fused_k, fused_tile=(8, 16)
        )
        got = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)


@pytest.mark.parametrize("npt", [10, 9])
def test_fused_ragged_zpatch_periodic_z_matches_xla(npt):
    """Ragged schedule through the in-kernel z-slab cadence (periodic
    self-neighbor z): patch application and export both at width w for
    every chunk, shorter chunks included."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 1
    kw = dict(
        devices=jax.devices()[:1], periodz=1, overlapz=8, npt=npt,
        quiet=True, dtype=jax.numpy.float32,
    )
    state, params = pc.setup(16, 32, 128, **kw)
    step = pc.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(A) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = pc.make_multi_step(
            params, nt, donate=False, fused_k=4, fused_tile=(8, 16)
        )
        got = [np.asarray(A) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)


def test_fused_deep_halo_matches_xla_multiblock():
    """k fused PT iterations + one width-k all-field slab exchange vs the
    per-iteration comm-lean path (interpret-mode kernel; 2 devices — the
    interpret-mode Pallas + shard_map deadlock constraint)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 2
    kw = dict(
        devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1, overlapx=4,
        npt=4, quiet=True, dtype=jax.numpy.float32,  # f64: outside envelope
    )
    state, params = pc.setup(16, 32, 128, **kw)
    step = pc.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = pc.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)


def test_fused_fallback_warns_and_matches_cadence():
    """A local block the kernel envelope rejects must warn once and run the
    XLA path at the same slab cadence — bit-identical to exchange_every=w."""
    # dtype pinned so the fallback fires for the documented y%8 shape
    # rejection, not the x64-itemsize check (the suite runs x64).
    kw = dict(overlapx=4, overlapy=4, overlapz=4, npt=4, quiet=True,
              dtype=jax.numpy.float32)
    state, params = pc.setup(10, 10, 10, **kw)
    step = pc.make_multi_step(params, 2, donate=False, exchange_every=2)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(10, 10, 10, **kw)
    with pytest.warns(RuntimeWarning, match="falling back to the XLA path"):
        stepf = pc.make_multi_step(params, 2, donate=False, fused_k=2)
        got = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        np.testing.assert_array_equal(g, r, err_msg=name)


def test_fused_validation():
    state, params = pc.setup(
        16, 32, 128, devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
        npt=4, quiet=True,
    )
    with pytest.raises(ValueError, match="deep halo"):
        pc.make_multi_step(params, 2, fused_k=2)
    igg.finalize_global_grid()
    kw = dict(overlapx=4, overlapy=4, overlapz=4, quiet=True)
    # npt=5 with fused_k=2 is no longer rejected: the ragged schedule (one
    # leading XLA iteration + [2, 2]) runs it — equivalence covered by
    # test_fused_ragged_npt_matches_xla.
    state, params = pc.setup(10, 10, 10, npt=4, **kw)
    with pytest.raises(ValueError, match="conflicts"):
        pc.make_multi_step(params, 2, fused_k=2, exchange_every=4)
    with pytest.raises(ValueError, match="pass both bx and by"):
        pc.make_multi_step(params, 2, fused_k=2, fused_tile=(8, None))
    igg.finalize_global_grid()
    state, params = pc.setup(10, 10, 10, npt=4, hide_comm=True, **kw)
    with pytest.raises(ValueError, match="mutually exclusive"):
        pc.make_multi_step(params, 2, fused_k=2)
    igg.finalize_global_grid()


def test_fused_zpatch_deep_halo_z_split_matches_xla():
    """The in-kernel z-slab PT cadence (z-dim decomposition) vs the
    per-iteration comm-lean path (interpret-mode kernel, 2 devices on z)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 2
    kw = dict(
        devices=jax.devices()[:2], dimx=1, dimy=1, dimz=2, overlapz=4,
        npt=4, quiet=True, dtype=jax.numpy.float32,
    )
    state, params = pc.setup(16, 32, 128, **kw)
    step = pc.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = pc.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(igg.gather(A)) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)


def test_fused_zpatch_periodic_z_matches_xla():
    """Same cadence on the periodic self-neighbor z config (1 device)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    nt = 2
    kw = dict(
        devices=jax.devices()[:1], periodz=1, overlapz=4, npt=4, quiet=True,
        dtype=jax.numpy.float32,
    )
    state, params = pc.setup(16, 32, 128, **kw)
    step = pc.make_multi_step(params, nt, donate=False)
    ref = [np.asarray(A) for A in jax.block_until_ready(step(*state))]
    igg.finalize_global_grid()

    state, params = pc.setup(16, 32, 128, **kw)
    with pallas_force_interpret():
        stepf = pc.make_multi_step(
            params, nt, donate=False, fused_k=2, fused_tile=(8, 16)
        )
        got = [np.asarray(A) for A in jax.block_until_ready(stepf(*state))]
    igg.finalize_global_grid()
    for name, g, r in zip(("T", "Pf", "qDx", "qDy", "qDz"), got, ref):
        np.testing.assert_allclose(g, r, rtol=2e-5, atol=2e-5, err_msg=name)
