"""Porous-convection model tests (pseudo-transient Darcy + temperature)."""

import numpy as np

import jax

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import porous_convection3d as pc

from tests.test_models_diffusion import dedup_global


def _run(nt, nx, devices=None, npt=8):
    state, params = pc.setup(nx, nx, nx, devices=devices, npt=npt)
    gg = igg.get_global_grid()
    dims = gg.dims
    step = pc.make_step(params)
    for _ in range(nt):
        state = jax.block_until_ready(step(*state))
    out = {}
    for name, A in zip(("T", "Pf", "qDx", "qDy", "qDz"), state):
        shp = igg.local_shape(A)
        ol = tuple(igg.ol(d, A) for d in range(3))
        g = np.asarray(igg.gather(A))
        out[name] = dedup_global(g, dims, shp, ol) if max(dims) > 1 else g
    igg.finalize_global_grid()
    return out


def test_multi_matches_single():
    nt, nx = 4, 10
    multi = _run(nt, nx)  # 2x2x2, global 18^3
    single = _run(nt, 18, devices=[jax.devices()[0]])
    for k in multi:
        np.testing.assert_allclose(multi[k], single[k], rtol=1e-11, atol=1e-12, err_msg=k)


def test_convection_starts_and_is_bounded():
    state, params = pc.setup(12, 12, 12, npt=8)
    step = pc.make_step(params)
    for _ in range(12):
        state = jax.block_until_ready(step(*state))
    T = np.asarray(igg.gather(pc.temperature(state)))
    qDz = np.asarray(igg.gather(state[4]))
    igg.finalize_global_grid()
    assert np.isfinite(T).all() and np.isfinite(qDz).all()
    # Dirichlet walls intact (frozen boundary planes):
    assert abs(T[:, :, 0].mean() - 0.5) < 0.1
    assert abs(T[:, :, -1].mean() + 0.5) < 0.1
    # buoyancy must have driven an upward Darcy flux somewhere
    assert qDz.max() > 1e-8
    # temperature stays within the physical contrast (+ perturbation margin)
    assert T.max() <= 0.65 and T.min() >= -0.65
