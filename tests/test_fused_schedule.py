"""Group-schedule loop shape (`models/_fused.run_group_schedule`) and the
deep-z envelope gate coupling (`ops/pallas_stencil`) — advisor r4 findings."""

import jax
import jax.numpy as jnp
import pytest

from implicitglobalgrid_tpu.models._fused import run_group_schedule
from implicitglobalgrid_tpu.ops.pallas_stencil import fused_support_error


def _traced(chunks):
    """Run a schedule under jit; return (result, body trace count)."""
    calls = []

    def body(ki, c):
        calls.append(ki)
        return c + ki

    out = jax.jit(lambda c: run_group_schedule(chunks, body, c))(jnp.float32(0))
    return float(out), len(calls)


def test_short_schedule_fully_unrolled():
    out, ncalls = _traced([2] * 5)
    assert out == 10.0
    assert ncalls == 5  # no fori_loop at all


def test_long_uniform_schedule_keeps_unrolled_groups():
    """A 12-group production schedule must keep the unrolled-group pipelining
    win on unroll_limit groups, fori-looping only the excess (advisor r4:
    the old shape sent the whole run through the fori_loop, silently losing
    the documented 15-30% speedup for nsteps=24 at fused_k=2)."""
    out, ncalls = _traced([2] * 12)
    assert out == 24.0
    # 8 unrolled traces + the fori body trace(s); strictly fewer than full
    # unroll, strictly more than fori-only (1-2 traces).
    assert 9 <= ncalls <= 10


def test_ragged_schedule_counts_tail_against_limit():
    out, ncalls = _traced([6] * 10 + [4])
    assert out == 64.0
    # 7 unrolled prefix + 1 ragged tail + fori trace(s)
    assert 9 <= ncalls <= 10


def test_all_or_nothing_shape_for_xla_cadences():
    """`fori_excess_only=False` (the porous XLA cadence): a uniform run past
    the limit is ENTIRELY fori-looped — the fori boundary is the fusion
    barrier its bit-identity contract relies on — while a ragged tail and
    within-limit runs still unroll."""
    calls = []

    def body(ki, c):
        calls.append(ki)
        return c + ki

    out = jax.jit(
        lambda c: run_group_schedule(
            [2] * 3, body, c, unroll_limit=1, fori_excess_only=False
        )
    )(jnp.float32(0))
    assert float(out) == 6.0
    assert len(calls) <= 2  # fori trace only, no unrolled groups
    calls.clear()
    out = jax.jit(
        lambda c: run_group_schedule(
            [6, 4], body, c, unroll_limit=1, fori_excess_only=False
        )
    )(jnp.float32(0))
    assert float(out) == 10.0
    assert calls == [6, 4]  # prefix of one group: fully unrolled


def test_deep_z_gate_and_budget_jointly_cover_by128():
    """Advisor r4: the probed crash predicate (`_deep_z_crash`: by>=128, k>4,
    n2>=512) and the VMEM budget are coupled — by=128 configs the predicate
    admits (k <= 4) at deep z must be stopped by the budget instead.  Pin
    every by=128 deep-z combination to a rejection by ONE of the two gates,
    and the probed-safe point to acceptance."""
    for k, n2 in [(2, 1024), (4, 1024), (6, 512), (6, 1024)]:
        err = fused_support_error((64, 256, n2), k, 4, 32, 128)
        assert err is not None, f"(k={k}, n2={n2}) must be rejected"
    # the hardware-validated deep-z rung stays in the envelope
    assert fused_support_error((64, 256, 512), 4, 4, 32, 128) is None
