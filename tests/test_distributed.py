"""2-process `jax.distributed` test — the reference's multi-rank coverage.

The reference runs its whole suite under real MPI with any rank count
(`/root/reference/test/runtests.jl:8-31`); the equivalent here is spawning
two coordinator-connected JAX processes on localhost (CPU backend, 4 virtual
devices each) and checking the distributed result against a single-process
run of the *same global problem* on this process's 8-device mesh.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

NX = 8
NSTEPS = 3

_here = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pair_env():
    """Clean slate for spawned worker pairs: no inherited TPU plugin
    registration, repo importable, no conftest side effects (workers
    configure jax themselves, before first device use), and no leaked
    fault spec from an outer harness."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("IGG_FAULT_INJECT", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.path.dirname(_here), env.get("PYTHONPATH")) if p
    )
    return env


@pytest.fixture(scope="module")
def dist_out_path(tmp_path_factory):
    port = _free_port()
    out = str(tmp_path_factory.mktemp("dist") / "gathered.npy")
    env = _pair_env()
    worker = os.path.join(_here, "_distributed_worker.py")
    logdir = tmp_path_factory.mktemp("dist_logs")
    logs = [open(logdir / f"worker{pid}.log", "w+") for pid in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port), out],
            env=env,
            stdout=logs[pid],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    try:
        for pid, p in enumerate(procs):
            p.wait(timeout=480)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    finally:
        for f in logs:
            f.flush()
    outs = []
    for pid, (p, f) in enumerate(zip(procs, logs)):
        f.seek(0)
        outs.append((pid, p.returncode, f.read()))
        f.close()
    for pid, rc, stdout in outs:
        assert rc == 0, f"worker {pid} failed (rc={rc}):\n{stdout}"
        assert f"WORKER {pid} OK" in stdout
    return out


@pytest.fixture(scope="module")
def dist4_out_path(tmp_path_factory):
    """FOUR coordinator-connected processes, one virtual device each, on a
    2x2x1 mesh: two SIMULTANEOUS process boundaries (x and y) through the
    grid — the worker runs the compact scenario (fused-cadence exchange
    with corner carry-over, fill-in-place gather, coalesced-vs-per-field
    bit identity; ISSUE 5 satellite).  Shapes stay tiny (local 8^3, 4
    steps) so the tier-1 budget holds."""
    nproc = 4
    port = _free_port()
    out = str(tmp_path_factory.mktemp("dist4") / "gathered.npy")
    env = _pair_env()
    worker = os.path.join(_here, "_distributed_worker.py")
    logdir = tmp_path_factory.mktemp("dist4_logs")
    logs = [open(logdir / f"worker{pid}.log", "w+") for pid in range(nproc)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(port), out,
             "2x2x1"],
            env=env,
            stdout=logs[pid],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(nproc)
    ]
    try:
        for pid, p in enumerate(procs):
            p.wait(timeout=480)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    finally:
        for f in logs:
            f.flush()
    outs = []
    for pid, (p, f) in enumerate(zip(procs, logs)):
        f.seek(0)
        outs.append((pid, p.returncode, f.read()))
        f.close()
    for pid, rc, stdout in outs:
        assert rc == 0, f"worker {pid} failed (rc={rc}):\n{stdout}"
        assert f"WORKER {pid} OK" in stdout
    return out


def test_four_process_2x2_mesh_matches_single_process(dist4_out_path):
    """The 4-process 2x2 run's fused-cadence result (two real gloo process
    boundaries, corner carry-over through both) must reproduce the same
    global problem run single-process with the SAME (2,2,1) decomposition
    on this process's own devices."""
    import warnings

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(
        NX, NX, NX, dimx=2, dimy=2, dimz=1, devices=jax.devices()[:4],
        overlapx=4, overlapy=4, overlapz=4, quiet=True,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        stepc = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
        state = jax.block_until_ready(stepc(*state))
    expected = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()

    got = np.load(dist4_out_path)
    assert got.shape == expected.shape
    assert got.dtype == expected.dtype
    np.testing.assert_allclose(got, expected, rtol=1e-13, atol=1e-13)


def test_two_process_matches_single_process(dist_out_path):
    """The 2-process distributed run must reproduce the single-process run."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    # Same global problem on this process's own 8-device mesh: local 8^3,
    # 8 blocks, dims (2,2,2) in both setups.
    state, params = diffusion3d.setup(NX, NX, NX, quiet=True)
    step = diffusion3d.make_step(params)
    for _ in range(NSTEPS):
        state = jax.block_until_ready(step(*state))
    expected = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()

    got = np.load(dist_out_path)
    assert got.shape == expected.shape
    assert got.dtype == expected.dtype
    np.testing.assert_allclose(got, expected, rtol=1e-13, atol=1e-13)


def test_two_process_fused_cadence_matches_single_process(dist_out_path):
    """The production fused cadence's COMMUNICATION across a REAL process
    boundary (VERDICT r4 #3): the worker ran `make_multi_step(fused_k=2)` on
    its f64 deep-halo grid — the documented fallback runs the XLA cadence at
    the kernel path's exact exchange schedule (one width-2 slab exchange per
    2 steps), with gloo hops inside every exchange.  The same problem with
    the same decomposition single-process must agree bitwise-tight.  (The
    Pallas kernel itself cannot cross a process boundary in interpret mode —
    the interpreter barriers all global devices on local threads; see the
    worker's comment — and its arithmetic equivalence to the XLA cadence is
    pinned single-process in test_models_diffusion.py.)"""
    import warnings

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(
        NX, NX, NX, overlapx=4, overlapy=4, overlapz=4, quiet=True
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        stepc = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
        state = jax.block_until_ready(stepc(*state))
    expected = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()

    got = np.load(dist_out_path + ".fused.npy")
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-13, atol=1e-13)


def test_two_process_hide_communication_matches_single_process(dist_out_path):
    """`hide_communication` (overlap-scheduled exchange) across the real
    process boundary, against the same 8-block problem single-process."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(NX, NX, NX, hide_comm=True, quiet=True)
    step = diffusion3d.make_step(params, donate=False)
    for _ in range(NSTEPS):
        state = jax.block_until_ready(step(*state))
    expected = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()

    got = np.load(dist_out_path + ".hc.npy")
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-13, atol=1e-13)


@pytest.mark.fault
def test_worker_crash_restart_from_checkpoint(tmp_path):
    """Kill one worker mid-run; restart the pair from the last checkpoint.

    The acceptance path of the resilience subsystem end to end, across a
    REAL process boundary: (1) an uninterrupted 2-process run is the
    reference; (2) the same run with ``IGG_FAULT_INJECT=
    worker_crash:step4:proc1`` loses process 1 right after the step-4
    checkpoint completes (exit status 17; the orphaned process 0 is
    reaped); (3) a restarted pair resumes from the step-4 checkpoint and
    finishes.  The resumed run's gathered field must be BIT-identical to
    the uninterrupted one.
    """
    import shutil

    worker = os.path.join(_here, "_resilience_worker.py")
    env_base = _pair_env()

    def spawn_pair(mode, ckptdir, out, extra_env=None):
        env = dict(env_base)
        env.update(extra_env or {})
        port = _free_port()
        logdir = tmp_path / f"logs_{mode}"
        logdir.mkdir(exist_ok=True)
        logs = [open(logdir / f"worker{pid}.log", "w+") for pid in range(2)]
        procs = [
            subprocess.Popen(
                [
                    sys.executable, worker, str(pid), "2", str(port),
                    mode, str(ckptdir), str(out),
                ],
                env=env,
                stdout=logs[pid],
                stderr=subprocess.STDOUT,
                text=True,
            )
            for pid in range(2)
        ]
        return procs, logs

    def read_logs(procs, logs):
        outs = []
        for p, f in zip(procs, logs):
            f.flush()
            f.seek(0)
            outs.append((p.returncode, f.read()))
            f.close()
        return outs

    def finish_pair(procs, logs, what):
        try:
            for p in procs:
                p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs = read_logs(procs, logs)
        for pid, (rc, log) in enumerate(outs):
            assert rc == 0, f"{what} worker {pid} failed (rc={rc}):\n{log}"
            assert f"WORKER {pid} OK" in log
        return outs

    # (1) uninterrupted reference run
    expected_path = tmp_path / "expected.npy"
    procs, logs = spawn_pair("normal", tmp_path / "ckpt_ref", expected_path)
    finish_pair(procs, logs, "reference")
    expected = np.load(expected_path)

    # (2) crash run: worker 1 hard-exits after the step-4 checkpoint
    crash_dir = tmp_path / "ckpt_crash"
    procs, logs = spawn_pair(
        "crash",
        crash_dir,
        tmp_path / "never.npy",
        extra_env={"IGG_FAULT_INJECT": "worker_crash:step4:proc1"},
    )
    try:
        procs[1].wait(timeout=240)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    # the survivor loses its peer mid-collective; reap it like an
    # orchestrator would
    try:
        procs[0].wait(timeout=30)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        procs[0].wait()
    outs = read_logs(procs, logs)
    assert procs[1].returncode == 17, (
        f"worker 1 should have crashed with the injected status 17, got "
        f"{procs[1].returncode}:\n{outs[1][1]}"
    )
    assert "WORKER 1 OK" not in outs[1][1]
    # the crash left a COMPLETE step-4 checkpoint (meta.json written after
    # the all-process barrier, before the injected exit)
    from implicitglobalgrid_tpu.utils.checkpoint import latest_checkpoint

    latest = latest_checkpoint(crash_dir)
    assert latest is not None and latest.endswith("step_00000004"), latest

    # (3) restart the pair against the same checkpoint dir: resumes at the
    # checkpointed step and must finish bit-identical to the reference
    got_path = tmp_path / "resumed.npy"
    procs, logs = spawn_pair("resume", crash_dir, got_path)
    finish_pair(procs, logs, "resume")
    got = np.load(got_path)
    assert got.shape == expected.shape and got.dtype == expected.dtype
    np.testing.assert_array_equal(got, expected)
    shutil.rmtree(tmp_path / "ckpt_ref", ignore_errors=True)


@pytest.mark.fault
def test_elastic_restart_shrunk_topology(tmp_path):
    """Crash + damaged newest generation -> shrunk-topology restart.

    The elastic-restart acceptance path across a REAL process boundary
    (docs/robustness.md): a 2-process gloo pair (dims (2,1,1), local 8^3,
    nxyz_g (14,8,8)) runs with ``IGG_FAULT_INJECT=worker_crash:step4:proc1,
    ckpt_corrupt:step4`` — process 1 dies right after the step-4 checkpoint
    AND that newest generation is bit-flipped in place.  The restart runs on
    ONE process (1 device, local (14,8,8) — the same implicit global grid),
    where `latest_checkpoint` must fall back to the step-2 generation and
    `restore_checkpoint` must reshard the 2-process shards onto the shrunk
    topology.  The finished run must match a never-crashed single-grid
    oracle of the same global problem (decomposition invariance).
    """
    worker = os.path.join(_here, "_resilience_worker.py")
    env = _pair_env()
    env["IGG_FAULT_INJECT"] = "worker_crash:step4:proc1,ckpt_corrupt:step4"
    crash_dir = tmp_path / "ckpt_crash"
    port = _free_port()
    logdir = tmp_path / "logs_crash"
    logdir.mkdir()
    logs = [open(logdir / f"worker{pid}.log", "w+") for pid in range(2)]
    procs = [
        subprocess.Popen(
            [
                sys.executable, worker, str(pid), "2", str(port),
                "crash", str(crash_dir), str(tmp_path / "never.npy"),
            ],
            env=env,
            stdout=logs[pid],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    try:
        procs[1].wait(timeout=240)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    # the survivor loses its peer mid-collective; reap it like a supervisor
    try:
        procs[0].wait(timeout=30)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        procs[0].wait()
    for f in logs:
        f.flush()
        f.seek(0)
    outs = [f.read() for f in logs]
    for f in logs:
        f.close()
    assert procs[1].returncode == 17, (
        f"worker 1 should have crashed with status 17, got "
        f"{procs[1].returncode}:\n{outs[1]}"
    )
    assert "IGG_FAULT_INJECT(ckpt_corrupt)" in outs[0], outs[0]

    from implicitglobalgrid_tpu.utils.checkpoint import latest_checkpoint

    # the newest published generation is step 4, but it is damaged: the
    # verified scan must fall back to step 2
    newest = latest_checkpoint(crash_dir, verify=False)
    assert newest is not None and newest.endswith("step_00000004"), newest
    latest = latest_checkpoint(crash_dir)
    assert latest is not None and latest.endswith("step_00000002"), latest

    # never-crashed oracle: the same global problem on ONE device
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.utils import resilience

    def single_grid_run(ckptdir):
        igg.init_global_grid(14, 8, 8, quiet=True, devices=jax.devices()[:1])
        assert igg.get_global_grid().nxyz_g == (14, 8, 8)
        state, params = diffusion3d.setup(14, 8, 8, init_grid=False)
        step = diffusion3d.make_step(params)
        guard = resilience.RunGuard(
            checkpoint_every=2 if ckptdir else None,
            checkpoint_dir=ckptdir,
            names=("T", "Cp"),
        )
        state = resilience.guarded_time_loop(
            step, state, 6, guard=guard, sync_every_step=True
        )
        T = np.asarray(jax.block_until_ready(state[0]))
        igg.finalize_global_grid()
        return T

    oracle = single_grid_run(None)
    # shrunk-topology restart: resumes at step 2 (elastic reshard of the
    # 2-process shards), finishes the remaining 4 steps on 1 process
    got = single_grid_run(str(crash_dir))
    assert got.shape == oracle.shape
    np.testing.assert_allclose(got, oracle, rtol=1e-13, atol=1e-13)


def test_gather_invalid_root_raises():
    import implicitglobalgrid_tpu as igg

    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.zeros((NX, NX, NX))
    with pytest.raises(ValueError, match="root"):
        igg.gather(T, root=jax.process_count())
    with pytest.raises(ValueError, match="root"):
        igg.gather(T, root=-1)
    igg.finalize_global_grid()


def test_two_process_rank_tagged_telemetry_events(dist_out_path):
    """The 2-process gloo leg of the observability acceptance
    (docs/observability.md): both ranks of the worker pair must have
    written their OWN JSONL event file into the shared telemetry
    directory, every line rank/pid/coords-tagged and schema-complete, with
    the two ranks disagreeing exactly where they must (rank, pid, coords)."""
    from implicitglobalgrid_tpu.utils.telemetry import read_events

    tdir = dist_out_path + ".telemetry"
    f0 = os.path.join(tdir, "events.jsonl")
    f1 = os.path.join(tdir, "events.p1.jsonl")
    assert os.path.isfile(f0), f"rank 0 wrote no event log under {tdir}"
    assert os.path.isfile(f1), f"rank 1 wrote no event log under {tdir}"
    e0, e1 = read_events(f0), read_events(f1)
    checks = []
    for rank, events in ((0, e0), (1, e1)):
        for e in events:
            assert {"ts", "type", "rank", "pid", "coords"} <= set(e), e
        mine = [e for e in events if e["type"] == "worker.check"]
        assert len(mine) == 1, (rank, [e["type"] for e in events])
        assert mine[0]["rank"] == rank
        checks.append(mine[0])
    # Distinct processes, distinct blocks: pid and grid coords must differ.
    assert checks[0]["pid"] != checks[1]["pid"]
    assert checks[0]["coords"] != checks[1]["coords"]
    assert checks[0]["coords"] is not None and checks[1]["coords"] is not None


def test_two_process_merged_trace(dist_out_path):
    """ISSUE 10 acceptance: the real 2-process gloo run yields ONE merged
    Chrome trace — both ranks' ``igg.step`` and halo-exchange spans on the
    shared barrier-aligned clock, loadable as valid JSON, with per-track
    monotonic timestamps and the alignment honesty bound recorded."""
    import glob
    import json

    from implicitglobalgrid_tpu.utils import tracing

    tdir = dist_out_path + ".telemetry"
    files = sorted(glob.glob(os.path.join(tdir, "trace.p*.json")))
    assert len(files) == 2, f"expected both ranks' span files, got {files}"
    merged = tracing.merge_trace_files(files)
    # Valid Chrome-trace JSON: serializable, re-loadable, and clean under
    # the validator (which includes per-track ts monotonicity).
    doc = json.loads(json.dumps(merged))
    assert tracing.validate_chrome_trace(doc) == []
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in spans} == {0, 1}
    for rank in (0, 1):
        names = {e["name"] for e in spans if e["pid"] == rank}
        assert "igg.step" in names, (rank, sorted(names))
        assert "igg_halo_exchange" in names, (rank, sorted(names))
        # the step spans carry their model/step tags into the args field
        steps = [
            e["args"]["step"] for e in spans
            if e["pid"] == rank and e["name"] == "igg.step"
        ]
        assert steps == sorted(steps) and len(steps) >= 4, steps
    align = doc["otherData"]["clock_alignment"]
    assert align["anchor_rank"] == 0
    for rank in ("0", "1"):
        per = align["per_rank"][rank]
        assert per["barrier_aligned"] is True
        assert isinstance(per["uncertainty_s"], (int, float))
        assert per["uncertainty_s"] >= 0


def test_two_process_device_merged_trace(dist_out_path):
    """ISSUE 15 acceptance, the real-boundary leg: both ranks of the gloo
    pair armed ``IGG_PROFILE=steps:2-3`` around the instrumented loop, so
    the run dir holds one capture meta + device trace per rank — and
    ``igg_trace.py merge --device`` must join BOTH ranks' device tracks
    into the ONE barrier-aligned Chrome trace, still valid, each rank's
    device ops on its own pid with the anchor honesty bound recorded."""
    import glob
    import json

    from implicitglobalgrid_tpu.utils import profiling, tracing

    tdir = dist_out_path + ".telemetry"
    metas = profiling.find_capture_metas(tdir)
    assert len(metas) == 2, f"expected both ranks' capture metas, got {metas}"
    files = sorted(glob.glob(os.path.join(tdir, "trace.p*.json")))
    doc = tracing.merge_trace_files(files)
    profiling.attach_device_tracks(doc, metas)
    doc = json.loads(json.dumps(doc))  # serializable + re-loadable
    assert tracing.validate_chrome_trace(doc) == []
    device = [
        e for e in doc["traceEvents"]
        if e.get("ph") == "X" and (e.get("args") or {}).get("hlo_op")
    ]
    assert {e["pid"] for e in device} == {0, 1}, (
        "both ranks' device tracks must be present"
    )
    for rank in (0, 1):
        ops = [e for e in device if e["pid"] == rank]
        assert all(
            e["tid"] >= profiling.DEVICE_TID_BASE for e in ops
        ), "device ops must ride dedicated device tids"
        assert all(e["args"].get("igg_scope") for e in ops)
    dev_align = doc["otherData"]["device_alignment"]
    assert set(dev_align["per_rank"]) == {"0", "1"}
    for rank in ("0", "1"):
        assert dev_align["per_rank"][rank]["n_ops"] > 0
