"""2-process `jax.distributed` test — the reference's multi-rank coverage.

The reference runs its whole suite under real MPI with any rank count
(`/root/reference/test/runtests.jl:8-31`); the equivalent here is spawning
two coordinator-connected JAX processes on localhost (CPU backend, 4 virtual
devices each) and checking the distributed result against a single-process
run of the *same global problem* on this process's 8-device mesh.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

NX = 8
NSTEPS = 3

_here = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def dist_out_path(tmp_path_factory):
    port = _free_port()
    out = str(tmp_path_factory.mktemp("dist") / "gathered.npy")
    env = dict(os.environ)
    # A clean slate for the children: no inherited TPU plugin registration,
    # repo importable, and no conftest side effects (workers configure jax
    # themselves, before first device use).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.path.dirname(_here), env.get("PYTHONPATH")) if p
    )
    worker = os.path.join(_here, "_distributed_worker.py")
    logdir = tmp_path_factory.mktemp("dist_logs")
    logs = [open(logdir / f"worker{pid}.log", "w+") for pid in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port), out],
            env=env,
            stdout=logs[pid],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    try:
        for pid, p in enumerate(procs):
            p.wait(timeout=480)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    finally:
        for f in logs:
            f.flush()
    outs = []
    for pid, (p, f) in enumerate(zip(procs, logs)):
        f.seek(0)
        outs.append((pid, p.returncode, f.read()))
        f.close()
    for pid, rc, stdout in outs:
        assert rc == 0, f"worker {pid} failed (rc={rc}):\n{stdout}"
        assert f"WORKER {pid} OK" in stdout
    return out


def test_two_process_matches_single_process(dist_out_path):
    """The 2-process distributed run must reproduce the single-process run."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    # Same global problem on this process's own 8-device mesh: local 8^3,
    # 8 blocks, dims (2,2,2) in both setups.
    state, params = diffusion3d.setup(NX, NX, NX, quiet=True)
    step = diffusion3d.make_step(params)
    for _ in range(NSTEPS):
        state = jax.block_until_ready(step(*state))
    expected = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()

    got = np.load(dist_out_path)
    assert got.shape == expected.shape
    assert got.dtype == expected.dtype
    np.testing.assert_allclose(got, expected, rtol=1e-13, atol=1e-13)


def test_two_process_fused_cadence_matches_single_process(dist_out_path):
    """The production fused cadence's COMMUNICATION across a REAL process
    boundary (VERDICT r4 #3): the worker ran `make_multi_step(fused_k=2)` on
    its f64 deep-halo grid — the documented fallback runs the XLA cadence at
    the kernel path's exact exchange schedule (one width-2 slab exchange per
    2 steps), with gloo hops inside every exchange.  The same problem with
    the same decomposition single-process must agree bitwise-tight.  (The
    Pallas kernel itself cannot cross a process boundary in interpret mode —
    the interpreter barriers all global devices on local threads; see the
    worker's comment — and its arithmetic equivalence to the XLA cadence is
    pinned single-process in test_models_diffusion.py.)"""
    import warnings

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(
        NX, NX, NX, overlapx=4, overlapy=4, overlapz=4, quiet=True
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        stepc = diffusion3d.make_multi_step(params, 4, donate=False, fused_k=2)
        state = jax.block_until_ready(stepc(*state))
    expected = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()

    got = np.load(dist_out_path + ".fused.npy")
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-13, atol=1e-13)


def test_two_process_hide_communication_matches_single_process(dist_out_path):
    """`hide_communication` (overlap-scheduled exchange) across the real
    process boundary, against the same 8-block problem single-process."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(NX, NX, NX, hide_comm=True, quiet=True)
    step = diffusion3d.make_step(params, donate=False)
    for _ in range(NSTEPS):
        state = jax.block_until_ready(step(*state))
    expected = np.asarray(igg.gather(diffusion3d.temperature(state)))
    igg.finalize_global_grid()

    got = np.load(dist_out_path + ".hc.npy")
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-13, atol=1e-13)


def test_gather_invalid_root_raises():
    import implicitglobalgrid_tpu as igg

    igg.init_global_grid(NX, NX, NX, quiet=True)
    T = igg.zeros((NX, NX, NX))
    with pytest.raises(ValueError, match="root"):
        igg.gather(T, root=jax.process_count())
    with pytest.raises(ValueError, match="root"):
        igg.gather(T, root=-1)
    igg.finalize_global_grid()
