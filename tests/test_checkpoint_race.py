"""The save-vs-prune race (ISSUE 14 satellite).

`RunGuard` prunes right after each save, a supervisor may prune a shared
directory while a rank is mid-save, and the staged-save design
(docs/robustness.md) is what makes that safe: an in-flight generation
lives under a hidden ``.step_*.tmp`` name until its manifest is complete,
so a concurrent `prune_checkpoints` can neither see it, count it against
retention, nor leave it as a manifest-less partial for
`latest_checkpoint` to pick.  These tests pin that contract by injecting
a prune (and a crash) into the middle of a save — between the shard
bytes landing and the manifest/rename publish — via the save's integrity
hook (`_crc32_file`, the last step before the manifest is assembled).
"""

import os

import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils import checkpoint as ckpt

NX = 8


@pytest.fixture
def grid():
    igg.init_global_grid(NX, NX, NX, quiet=True)
    yield igg.get_global_grid()


def _state():
    T0 = igg.zeros((NX, NX, NX))
    X, Y, Z = igg.coord_fields(T0, (0.37, 0.11, 0.53))
    return (X * 1.3 + Y * 0.7 + Z * 0.11,)


def _mid_save_hook(monkeypatch, hook):
    """Run ``hook(shard_path)`` at the point mid-save where this process's
    shard bytes are on disk but the manifest is NOT yet written and the
    generation is NOT yet published (the widest race window)."""
    real = ckpt._crc32_file
    fired = {"n": 0}

    def wrapper(path, *a, **kw):
        if fired["n"] == 0 and os.sep + "." in path:
            # first CRC of a STAGED (.step_*.tmp) shard = mid-save
            fired["n"] += 1
            hook(path)
        return real(path, *a, **kw)

    monkeypatch.setattr(ckpt, "_crc32_file", wrapper)
    return fired


def test_concurrent_prune_mid_save_never_exposes_a_partial(
    grid, tmp_path, monkeypatch
):
    state = _state()
    d = str(tmp_path)
    p2 = ckpt.save_checkpoint(d, state, 2)
    p4 = ckpt.save_checkpoint(d, state, 4)
    observed = {}

    def prune_mid_save(_path):
        # the race: retention fires while step 6 is staging.  The staged
        # generation must be invisible to the scan...
        observed["steps"] = [s for s, _ in ckpt.checkpoint_steps(d)]
        observed["removed"] = ckpt.prune_checkpoints(d, keep=1)
        # ...and whatever latest_checkpoint picks AT THIS INSTANT must be
        # a complete, integrity-verified generation — never the partial.
        pick = ckpt.latest_checkpoint(d)
        observed["pick"] = pick
        observed["pick_problem"] = ckpt.verify_checkpoint(pick)

    fired = _mid_save_hook(monkeypatch, prune_mid_save)
    p6 = ckpt.save_checkpoint(d, state, 6)
    assert fired["n"] == 1, "the mid-save hook never fired"
    assert observed["steps"] == [2, 4]  # the staging dir stayed hidden
    assert observed["removed"] == [p2]
    assert observed["pick"] == p4 and observed["pick_problem"] is None
    # the completed save publishes atomically and wins cleanly
    assert ckpt.latest_checkpoint(d) == p6
    assert ckpt.verify_checkpoint(p6) is None
    restored, step, _ = ckpt.restore_checkpoint(p6, like=state)
    assert step == 6


def test_crash_mid_save_plus_prune_leaves_latest_valid(
    grid, tmp_path, monkeypatch
):
    """A save that DIES mid-flight (after pruning already ran against the
    directory) must leave no visible partial: `latest_checkpoint` keeps
    returning the newest COMPLETE generation, and the torn staging dir
    never matches the ``step_*`` scan."""
    state = _state()
    d = str(tmp_path)
    ckpt.save_checkpoint(d, state, 2)
    p4 = ckpt.save_checkpoint(d, state, 4)

    def prune_then_die(_path):
        ckpt.prune_checkpoints(d, keep=1)
        raise RuntimeError("injected crash mid-save")

    _mid_save_hook(monkeypatch, prune_then_die)
    with pytest.raises(RuntimeError, match="injected crash"):
        ckpt.save_checkpoint(d, state, 6)
    # the torn generation is invisible; the newest complete one wins
    assert [s for s, _ in ckpt.checkpoint_steps(d)] == [4]
    assert ckpt.latest_checkpoint(d) == p4
    assert ckpt.verify_checkpoint(p4) is None
    # the hidden staging residue exists but can never be picked
    residue = [n for n in os.listdir(d) if n.startswith(".step_")]
    assert residue  # the crash really did leave a torn staging dir behind


def test_prune_keep1_cannot_delete_the_generation_being_replaced(
    grid, tmp_path, monkeypatch
):
    """keep=1 with a single existing generation while a newer one stages:
    the stager must not count toward retention, so the only complete
    generation survives until the new one PUBLISHES."""
    state = _state()
    d = str(tmp_path)
    p2 = ckpt.save_checkpoint(d, state, 2)

    def prune_mid_save(_path):
        assert ckpt.prune_checkpoints(d, keep=1) == []
        assert ckpt.latest_checkpoint(d) == p2

    _mid_save_hook(monkeypatch, prune_mid_save)
    p4 = ckpt.save_checkpoint(d, state, 4)
    assert ckpt.latest_checkpoint(d) == p4
    assert ckpt.prune_checkpoints(d, keep=1) == [p2]
