"""The network-facing serving plane (ISSUE 12; docs/serving.md).

Covers the admission accept/reject matrix (quota exhaustion, queue
backpressure, SLO breach — `admission.decide` as a PURE function of a
synthetic gauge view, plus the live controller over real gauges), the
429 ``Retry-After`` contract, the HTTP surface end to end on a loopback
ephemeral port, graceful drain (zero orphaned slots), the autoscaler
decision function's purity/determinism and sustain gating, and the
resize-checkpoint → `elastic_resume` round trip (live members adopted
mid-budget, queued members rebuilt from specs, digests bit-identical to
an undisturbed run).  The real 2-process + supervised-restart legs are
the soak ``frontdoor`` scenario (`scripts/soak.py --quick`).
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion3d
from implicitglobalgrid_tpu.serving import (
    AdmissionController,
    AdmissionPolicy,
    AutoscalePolicy,
    Autoscaler,
    FrontDoor,
    Request,
    Rung,
    ServingLoop,
)
from implicitglobalgrid_tpu.serving import admission as adm
from implicitglobalgrid_tpu.serving import autoscale as asc
from implicitglobalgrid_tpu.serving import frontdoor as fdm
from implicitglobalgrid_tpu.utils import liveplane as lp
from implicitglobalgrid_tpu.utils import telemetry as tele
from implicitglobalgrid_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    for knob in ("IGG_TENANT_QUOTA", "IGG_FRONTDOOR_QUEUE_MAX",
                 "IGG_FRONTDOOR_SLO_P99_S", "IGG_AUTOSCALE_QUEUE_HIGH",
                 "IGG_AUTOSCALE_SUSTAIN", "IGG_SERVE_PORT", "IGG_SERVE_HOST",
                 "IGG_METRICS_PORT", "IGG_RESULT_KEEP", "IGG_RESULT_TTL_S"):
        monkeypatch.delenv(knob, raising=False)
    tele.reset()
    tracing.reset()
    lp.reset()
    yield
    lp.reset()
    tele.reset()
    tracing.reset()


NX = 8


def _pool(capacity=2, **kw):
    igg.init_global_grid(NX, NX, NX, quiet=True)
    _, params = diffusion3d.setup(NX, NX, NX, init_grid=False)
    return ServingLoop(diffusion3d, params, capacity=capacity,
                       steps_per_round=1, **kw)


def _member(scale=1.0):
    state, _ = diffusion3d.setup(NX, NX, NX, init_grid=False, ic_scale=scale)
    return state


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as r:
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        return e.code, {}


def _post(port, path, doc):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read().decode() or "{}"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}"), dict(e.headers)
    except OSError:
        return 0, {}, {}  # door closed (mid-resize)


# -- admission: the pure decision function ------------------------------------


def test_decide_accept_reject_matrix():
    policy = AdmissionPolicy(tenant_rate=1.0, tenant_burst=2.0,
                             queue_max=4, slo_p99_s=0.5)
    ok = {"queue_depth": 1, "round_p99_s": 0.1, "tenant_tokens": 2.0,
          "critical_alert": False}
    assert adm.decide(ok, policy) == {"admit": True, "reason": None}
    # evaluation order: slo (alert) > slo (p99) > backpressure > quota
    assert adm.decide(dict(ok, critical_alert=True), policy)["reason"] == "slo"
    assert adm.decide(dict(ok, round_p99_s=0.9), policy)["reason"] == "slo"
    assert adm.decide(dict(ok, queue_depth=4), policy)["reason"] == "backpressure"
    assert adm.decide(dict(ok, queue_depth=9), policy)["reason"] == "backpressure"
    assert adm.decide(dict(ok, tenant_tokens=0.3), policy)["reason"] == "quota"
    # a gate that is None is disabled
    open_policy = AdmissionPolicy()
    assert adm.decide(
        {"queue_depth": 10**6, "round_p99_s": 10**3, "tenant_tokens": None},
        open_policy,
    )["admit"] is True
    # pure: same inputs, same verdict, inputs untouched
    view = dict(ok, queue_depth=4)
    first = adm.decide(view, policy)
    assert first == adm.decide(view, policy)
    assert view == dict(ok, queue_depth=4)


def test_token_bucket_deterministic_refill():
    b = adm.TokenBucket(rate=2.0, burst=2.0)
    assert b.refill(0.0) == 2.0
    assert b.take() and b.take() and not b.take()
    assert b.refill(0.25) == pytest.approx(0.5)  # 0.25s * 2/s
    assert not b.take()
    assert b.seconds_until_token() == pytest.approx(0.25)
    assert b.refill(1.0) == pytest.approx(2.0)  # capped at burst
    assert b.take()


def test_retry_after_sanity():
    policy = AdmissionPolicy(queue_max=4)
    view = {"round_p50_s": 0.2, "queue_depth": 8, "capacity": 2}
    # backpressure: proportional to the excess queue over the drain rate
    ra = adm.retry_after_s(view, policy, "backpressure")
    assert ra >= 0.2
    deeper = adm.retry_after_s(dict(view, queue_depth=20), policy,
                               "backpressure")
    assert deeper > ra  # monotone in queue depth
    # quota: the bucket refill, floored at one round
    assert adm.retry_after_s(view, policy, "quota", bucket_wait_s=3.0) == 3.0
    assert adm.retry_after_s(view, policy, "quota", bucket_wait_s=0.01) == 0.2
    # slo: a few rounds, never the "retry immediately" storm
    assert adm.retry_after_s({}, policy, "slo") >= 1.0


def test_controller_quota_and_ledger():
    ctl = AdmissionController(
        AdmissionPolicy(tenant_rate=1.0, tenant_burst=1.0), clock=lambda: 0.0
    )
    view = {"queue_depth": 0}
    assert ctl.check("tA", now=0.0, view=view).admit
    d = ctl.check("tA", now=0.0, view=view)  # bucket empty at the same instant
    assert not d.admit and d.reason == "quota" and d.retry_after_s > 0
    # an unrelated tenant has its own bucket
    assert ctl.check("tB", now=0.0, view=view).admit
    # refill admits again
    assert ctl.check("tA", now=5.0, view=view).admit
    c = tele.snapshot()["counters"]
    assert c["frontdoor.admitted_total"] == 3
    assert c["frontdoor.rejected_total"] == 1
    assert c["frontdoor.rejected.quota"] == 1
    assert c["frontdoor.tenant.tA.admitted"] == 2
    assert c["frontdoor.tenant.tA.rejected"] == 1
    assert c["frontdoor.tenant.tB.admitted"] == 1


def test_gauge_view_reads_live_registry_and_alerts():
    tele.gauge("serving.queue_depth").set(7)
    tele.gauge("serving.active_members").set(3)
    tele.gauge("serving.capacity").set(4)
    view = adm.gauge_view(tick=False)
    assert view["queue_depth"] == 7 and view["active_members"] == 3
    assert view["capacity"] == 4 and view["critical_alert"] is False
    # an active CRITICAL alert flips the view bit
    class Critical(lp.Rule):
        name = "crit"
        severity = "critical"

        def check(self, ctx):
            return {"why": "test"}

    lp.get_engine().rules[:] = [Critical()]
    view = adm.gauge_view()  # tick=True evaluates the rule at admission time
    assert view["critical_alert"] is True


# -- autoscaler ---------------------------------------------------------------


def test_autoscale_decide_pure_and_deterministic():
    policy = AutoscalePolicy(
        ladder=(Rung(1, 2), Rung(2, 4)), queue_high=3, p99_high_s=1.0,
        sustain=2,
    )
    idle = {"queue_depth": 0, "active_members": 0, "capacity": 2}
    busy = {"queue_depth": 5, "active_members": 2, "capacity": 2}
    slow = {"queue_depth": 0, "active_members": 2, "capacity": 2,
            "round_p99_s": 3.0}
    assert asc.decide(idle, policy, 0) == "hold"  # no lower rung
    assert asc.decide(busy, policy, 0) == "up"
    assert asc.decide(slow, policy, 0) == "up"    # p99 breach votes up too
    assert asc.decide(busy, policy, 1) == "hold"  # already at the top
    assert asc.decide(idle, policy, 1) == "down"
    # occupancy that does not fit the lower rung blocks the down-vote
    assert asc.decide(dict(idle, active_members=3), policy, 1) == "hold"
    # deterministic + side-effect free
    view = dict(busy)
    assert asc.decide(view, policy, 0) == asc.decide(view, policy, 0)
    assert view == busy
    with pytest.raises(ValueError):
        asc.decide(idle, policy, 5)


def test_autoscaler_sustain_gates_the_action():
    policy = AutoscalePolicy(ladder=(Rung(1, 2), Rung(2, 4)), queue_high=3,
                             sustain=2)
    scaler = Autoscaler(policy, rung=0)
    busy = {"queue_depth": 5, "active_members": 2, "capacity": 2}
    idle = {"queue_depth": 0, "active_members": 0, "capacity": 2}
    assert scaler.observe(busy) is None          # streak 1 of 2
    assert scaler.observe(idle) is None          # broken streak resets
    assert scaler.observe(busy) is None
    action = scaler.observe(busy)                # sustained -> commits
    assert action and action["action"] == "up" and action["rung"] == 1
    assert action["target"] == {"nproc": 2, "capacity": 4}
    assert scaler.observe(busy) is None          # streak reset after commit
    down = Autoscaler(policy, rung=1)
    down.observe(idle)
    action = down.observe(idle)
    assert action and action["action"] == "down"
    assert action["target"] == {"nproc": 1, "capacity": 2}


def test_autoscale_policy_env_tier(monkeypatch):
    monkeypatch.setenv("IGG_AUTOSCALE_QUEUE_HIGH", "7")
    monkeypatch.setenv("IGG_AUTOSCALE_SUSTAIN", "5")
    policy = AutoscalePolicy.from_env([Rung(1, 2)])
    assert policy.queue_high == 7 and policy.sustain == 5
    # explicit kwargs win over env (the config precedence)
    policy = AutoscalePolicy.from_env([Rung(1, 2)], sustain=1)
    assert policy.sustain == 1


# -- the HTTP surface ---------------------------------------------------------


def test_http_submit_result_status_roundtrip():
    loop = _pool(capacity=2)
    fd = FrontDoor(loop, port=0)
    try:
        code, body, _ = _post(fd.port, "/v1/submit", {
            "tenant": "tA", "model": "diffusion3d",
            "params": {"max_steps": 3, "ic_scale": 1.1},
        })
        assert code == 202 and body["request_id"] == "r000000"
        rid = body["request_id"]
        code, view = _get(fd.port, f"/v1/result/{rid}")
        assert view["status"] == "pending"  # not yet synced into the pool
        assert fd.serve_rounds(max_rounds=5) == "rounds"
        code, view = _get(fd.port, f"/v1/result/{rid}")
        assert view["status"] == "done" and view["result"] == "completed"
        assert view["steps"] == 3
        assert len(view["digest"]["fields"]) == 2  # (T, Cp)
        # the digest is the de-duplicated global state's sha256
        res = loop.results[0]
        assert view["digest"] == fdm.state_digest(res.state)
        code, status = _get(fd.port, "/v1/status")
        assert status["requests"] == {"total": 1, "done": 1}
        assert status["active_members"] == 0 and status["rounds"] >= 3
        code, view = _get(fd.port, "/v1/result/nope")
        assert code == 404
        # the frontdoor ledger rides /healthz (liveplane satellite)
        code, health = _get(fd.port, "/healthz")
        assert health["frontdoor"]["admitted_total"] == 1
        assert health["serving"]["capacity"] == 2
        # per-tenant latency histogram rides the SLO window family
        snap = tele.snapshot()
        assert snap["histograms"]["frontdoor.request_seconds"]["count"] == 1
        assert snap["histograms"][
            "frontdoor.tenant.tA.request_seconds"
        ]["count"] == 1
        assert "window" in snap["histograms"]["frontdoor.request_seconds"]
    finally:
        fd.close()


def test_http_validation_rejects_before_admission():
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        bad = [
            {"params": {}},                                   # no max_steps
            {"params": {"max_steps": 0}},                     # bad budget
            {"params": {"max_steps": 2, "tol": 0.1}},         # no residual
            {"model": "porous_convection3d", "params": {"max_steps": 2}},
            {"size": [1, 2, 3], "params": {"max_steps": 2}},  # wrong grid
            {"params": {"max_steps": 2, "ic_scale": "x"}},
        ]
        for doc in bad:
            code, body, _ = _post(fd.port, "/v1/submit", doc)
            assert code == 400, (doc, code, body)
        assert tele.snapshot()["counters"]["frontdoor.invalid_total"] == len(bad)
        assert "frontdoor.admitted_total" not in tele.snapshot()["counters"]
    finally:
        fd.close()


def test_http_429_retry_after_on_quota_and_backpressure(monkeypatch):
    monkeypatch.setenv("IGG_TENANT_QUOTA", "0.001:1")  # one request, ever-ish
    # 3, not 1: the accepted spec counts as pending in the backpressure
    # view, and quota must be the gate that fires on the second submit
    monkeypatch.setenv("IGG_FRONTDOOR_QUEUE_MAX", "3")
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        doc = {"tenant": "tA", "params": {"max_steps": 2}}
        code, body, _ = _post(fd.port, "/v1/submit", doc)
        assert code == 202
        code, body, headers = _post(fd.port, "/v1/submit", doc)
        assert code == 429 and body["reason"] == "quota"
        assert int(headers["Retry-After"]) >= 1
        assert body["retry_after_s"] > 0
        # a different tenant passes quota but hits the queue backpressure
        # (the accepted spec is pending; the GAUGE moves once it is synced)
        fd.serve_rounds(max_rounds=1)
        tele.gauge("serving.queue_depth").set(5)
        fd.admission._view_at = None  # bust the TTL view cache: the gauge
        # write above must be visible to THIS check, not the next one
        code, body, headers = _post(
            fd.port, "/v1/submit", {"tenant": "tB", "params": {"max_steps": 2}}
        )
        assert code == 429 and body["reason"] == "backpressure"
        assert int(headers["Retry-After"]) >= 1
        c = tele.snapshot()["counters"]
        assert c["frontdoor.rejected.quota"] == 1
        assert c["frontdoor.rejected.backpressure"] == 1
        assert c["frontdoor.rejected_total"] == 2
    finally:
        fd.close()


def test_slo_breach_flips_backpressure_live():
    """The acceptance contract in miniature: a CRITICAL alert active in the
    rule engine (the stall injector's end state) must flip submissions to
    429 reason="slo" WITHOUT any serving-thread cooperation."""
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        class Critical(lp.Rule):
            name = "step_stall"
            severity = "critical"
            on = False

            def check(self, ctx):
                return {"why": "wedged"} if self.on else None

        rule = Critical()
        lp.get_engine().rules[:] = [rule]
        doc = {"tenant": "tA", "params": {"max_steps": 2}}
        code, _, _ = _post(fd.port, "/v1/submit", doc)
        assert code == 202
        # a heartbeat/scrape tick raises the alert; the admission check
        # reads the ACTIVE-alert bit fresh on every request (its snapshot
        # view is TTL-cached, the alert bit deliberately is not)
        rule.on = True
        lp.get_engine().tick()
        code, body, headers = _post(fd.port, "/v1/submit", doc)
        assert code == 429 and body["reason"] == "slo"
        assert int(headers["Retry-After"]) >= 1
        assert tele.snapshot()["counters"]["frontdoor.rejected.slo"] == 1
        rule.on = False  # episode over: the engine re-arms, the door opens
        lp.get_engine().tick()
        code, _, _ = _post(fd.port, "/v1/submit", doc)
        assert code == 202
    finally:
        fd.close()


# -- graceful drain -----------------------------------------------------------


def test_drain_leaves_zero_orphaned_slots():
    loop = _pool(capacity=3)
    members = [loop.submit(Request(state=_member(1.0 + 0.1 * i), max_steps=2))
               for i in range(3)]
    extra = loop.submit(Request(state=_member(1.5), max_steps=2))
    assert loop.active_members == 3 and len(loop.queue) == 1
    loop.drain_above = 1  # slots 1, 2 are retiring
    for _ in range(8):
        loop.run_round()
        if len(loop.results) == 4:
            break
    # retiring slots emptied and were NEVER refilled; the queued member ran
    # in slot 0; nobody was dropped
    assert loop.drained(1)
    assert all(not s.active for s in loop.slots[1:])
    assert set(loop.results) == {*members, extra}
    assert all(r.status == "completed" for r in loop.results.values())
    assert loop.results[extra].steps == 2


# -- resize checkpoint + elastic resume ---------------------------------------


def test_resize_and_elastic_resume_bit_identical(tmp_path):
    specs = [(1.0, 6), (1.1, 6), (1.2, 6)]
    # the undisturbed oracle
    oracle_loop = _pool(capacity=4)
    oracle_ids = [
        oracle_loop.submit(Request(state=_member(s), max_steps=m))
        for s, m in specs
    ]
    oracle_loop.run(max_rounds=30)
    oracle = {
        (s, m): fdm.state_digest(oracle_loop.results[mid].state)
        for (s, m), mid in zip(specs, oracle_ids)
    }
    igg.finalize_global_grid()

    loop = _pool(capacity=2)
    fd = FrontDoor(loop, port=0, checkpoint_dir=str(tmp_path))
    rids = []
    try:
        for s, m in specs:
            code, body, _ = _post(fd.port, "/v1/submit", {
                "tenant": "t", "params": {"max_steps": m, "ic_scale": s},
            })
            assert code == 202
            rids.append(body["request_id"])
        fd.serve_rounds(max_rounds=3)  # 2 live mid-budget, 1 still queued
        assert loop.active_members == 2 and len(loop.queue) == 1
        fd._execute_resize({"nproc": 1, "capacity": 3, "rung": 1,
                            "reason": "up"})
        plan = json.loads((tmp_path / fdm.RESIZE_PLAN).read_text())
        assert plan["capacity"] == 3 and plan["reason"] == "up"
    finally:
        fd.close()
    igg.finalize_global_grid()

    # "relaunch" at the plan's capacity: adopted live members continue
    # mid-budget, the queued one is rebuilt from its spec, ids survive
    loop2 = _pool(capacity=3)
    fd2 = FrontDoor(loop2, port=0, checkpoint_dir=str(tmp_path))
    try:
        assert fd2.elastic_resume() is True
        assert loop2.active_members == 3  # 2 adopted + 1 requeued-and-admitted
        adopted_steps = [s.steps for s in loop2.slots if s.active]
        assert sorted(adopted_steps) == [0, 3, 3]  # budgets survived
        fd2.serve_rounds(max_rounds=10)
        for rid, (s, m) in zip(rids, specs):
            view = fd2.result_view(rid)
            assert view and view["status"] == "done", (rid, view)
            assert view["steps"] == m
            assert view["digest"] == oracle[(s, m)], f"{rid} not bit-identical"
        counters = tele.snapshot()["counters"]
        assert counters["frontdoor.resizes_total"] == 1
        assert counters["frontdoor.resumes_total"] == 1
    finally:
        fd2.close()


def test_resume_refuses_overfull_pool(tmp_path):
    loop = _pool(capacity=2)
    fd = FrontDoor(loop, port=0, checkpoint_dir=str(tmp_path))
    try:
        for i in range(2):
            loop.submit(Request(state=_member(1.0 + i / 10), max_steps=9))
        fd.serve_rounds(max_rounds=1)
        fd._execute_resize({"nproc": 1, "capacity": 1, "rung": 0,
                            "reason": "down"})
    finally:
        fd.close()
    igg.finalize_global_grid()
    loop2 = _pool(capacity=1)
    fd2 = FrontDoor(loop2, port=0, checkpoint_dir=str(tmp_path))
    try:
        with pytest.raises(RuntimeError, match="drain"):
            fd2.elastic_resume()
    finally:
        fd2.close()


def test_frontdoor_requires_checkpoint_dir_for_autoscaling():
    loop = _pool(capacity=1)
    policy = AutoscalePolicy(ladder=(Rung(1, 1),), sustain=1)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        FrontDoor(loop, port=0, autoscaler=Autoscaler(policy))


def test_serve_rounds_resize_outcome_via_autoscaler(tmp_path, monkeypatch):
    """End to end on one process: sustained queue pressure -> the serve
    loop itself checkpoints, writes the plan and returns "resize"."""
    monkeypatch.setenv("IGG_AUTOSCALE_SUSTAIN", "1")
    loop = _pool(capacity=1)
    policy = AutoscalePolicy.from_env([Rung(1, 1), Rung(1, 2)], queue_high=2)
    fd = FrontDoor(loop, port=0, checkpoint_dir=str(tmp_path),
                   autoscaler=Autoscaler(policy, rung=0))
    try:
        for i in range(4):
            code, _, _ = _post(fd.port, "/v1/submit", {
                "tenant": "t", "params": {"max_steps": 8, "ic_scale": 1 + i / 10},
            })
            assert code == 202
        outcome = fd.serve_rounds(max_rounds=50)
        assert outcome == "resize"
        plan = json.loads((tmp_path / fdm.RESIZE_PLAN).read_text())
        assert plan["capacity"] == 2 and plan["reason"] == "up"
        # mid-resize the door refuses cheaply (the supervisor restart gap)
        code, body, _ = _post(fd.port, "/v1/submit", {
            "tenant": "t", "params": {"max_steps": 1},
        })
        assert code in (429, 0) or body.get("reason") == "resizing"
    finally:
        fd.close()


# -- cross-layer wiring -------------------------------------------------------


def test_publish_gauges_single_writer():
    loop = _pool(capacity=2)
    g = tele.snapshot()["gauges"]
    assert g["serving.capacity"] == 2 and g["serving.queue_depth"] == 0
    m = loop.submit(Request(state=_member(), max_steps=1))
    g = tele.snapshot()["gauges"]
    assert g["serving.active_members"] == 1
    loop.run_round()
    # retirement updates the gauges IMMEDIATELY (the satellite fix: the
    # old code left them stale until the next admit)
    g = tele.snapshot()["gauges"]
    assert g["serving.active_members"] == 0
    assert loop.results[m].status == "completed"


def test_tenant_histogram_cardinality_cap(monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY_MAX_TENANTS", "2")
    tele.tenant_histogram("a").record(0.1)
    tele.tenant_histogram("b").record(0.2)
    tele.tenant_histogram("c").record(0.3)  # over the cap: folds
    tele.tenant_histogram("d").record(0.4)
    hists = tele.snapshot()["histograms"]
    assert hists["frontdoor.tenant.a.request_seconds"]["count"] == 1
    assert hists["frontdoor.tenant.b.request_seconds"]["count"] == 1
    assert hists[tele.FRONTDOOR_TENANT_OVERFLOW]["count"] == 2
    assert not any("tenant.c" in k or "tenant.d" in k for k in hists)


def test_endpoint_file_published(monkeypatch, tmp_path):
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path))
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        doc = json.loads((tmp_path / fdm.endpoint_filename(0)).read_text())
        assert doc["port"] == fd.port and doc["rank"] == 0
        assert tele.snapshot()["gauges"]["frontdoor.port"] == fd.port
    finally:
        fd.close()


# -- HTTP hardening (ISSUE 14 satellite) --------------------------------------


def test_http_oversize_body_refused_with_structured_413():
    import http.client

    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fd.port, timeout=10)
        conn.putrequest("POST", "/v1/submit")
        conn.putheader("Content-Type", "application/json")
        conn.putheader("Content-Length", str(fdm.MAX_BODY_DEFAULT + 1))
        conn.endheaders()
        # the refusal must arrive WITHOUT the server buffering the body
        resp = conn.getresponse()
        body = json.loads(resp.read().decode())
        conn.close()
        assert resp.status == 413
        assert body["max_bytes"] == fdm.MAX_BODY_DEFAULT
        assert body["bytes"] == fdm.MAX_BODY_DEFAULT + 1
        assert tele.snapshot()["counters"]["frontdoor.oversize_total"] == 1
    finally:
        fd.close()


def test_http_malformed_content_length_is_structured_400():
    import http.client

    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        for bad in ("abc", "-5"):
            conn = http.client.HTTPConnection("127.0.0.1", fd.port,
                                              timeout=10)
            conn.putrequest("POST", "/v1/submit")
            conn.putheader("Content-Type", "application/json")
            conn.putheader("Content-Length", bad)
            conn.endheaders()
            resp = conn.getresponse()
            body = json.loads(resp.read().decode())
            conn.close()
            assert resp.status == 400, bad
            assert "Content-Length" in body["error"], body
    finally:
        fd.close()


def test_http_max_body_env_tier(monkeypatch):
    monkeypatch.setenv("IGG_SERVE_MAX_BODY", "64")
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        doc = {"tenant": "t", "model": "diffusion3d",
               "params": {"max_steps": 1, "ic_scale": 1.0,
                          "padding": "x" * 256}}
        code, body, _ = _post(fd.port, "/v1/submit", doc)
        assert code == 413 and body["max_bytes"] == 64
        # under the bound the request flows into normal validation
        code, body, _ = _post(fd.port, "/v1/submit",
                              {"params": {"max_steps": 1}})
        assert code == 202
    finally:
        fd.close()


def test_http_malformed_json_and_missing_fields_are_structured_400s():
    loop = _pool(capacity=1)
    fd = FrontDoor(loop, port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{fd.port}/v1/submit", data=b"{not json",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 400
        assert "bad JSON body" in json.loads(e.value.read().decode())["error"]
        # a missing params object is the validation 400, never a 500
        code, body, _ = _post(fd.port, "/v1/submit", {"tenant": "t"})
        assert code == 400 and "params" in body["error"]
    finally:
        fd.close()


def test_handler_socket_timeouts_armed():
    """The slow-loris hardening: every per-connection handler carries a
    socket timeout, so a client trickling bytes is dropped instead of
    pinning a handler thread forever (frontdoor AND the liveplane)."""
    handler = fdm._make_handler(object())
    assert handler.timeout == fdm.SOCKET_TIMEOUT_S > 0
    assert lp._Handler.timeout and lp._Handler.timeout > 0


def test_result_retention_bounds_a_flood(monkeypatch):
    """Regression (ISSUE 16): a tenant that floods submits and never
    fetches must not grow ``loop.results`` / the request ledger without
    bound.  Flooded-out results answer a structured 410 (distinct from
    the 404 a never-issued rid gets), and the expiry is COUNTED."""
    monkeypatch.setenv("IGG_RESULT_KEEP", "4")
    loop = _pool(capacity=2)
    fd = FrontDoor(loop, port=0)
    try:
        for _ in range(12):
            code, body, _ = _post(fd.port, "/v1/submit", {
                "tenant": "t", "model": "diffusion3d",
                "params": {"max_steps": 1},
            })
            assert code == 202
        fd.serve_rounds(max_rounds=40)
        assert fd._seen_results <= set(loop.results)  # harvest keeps it tight
        loop._prune_results()  # the last round's harvest was post-prune
        assert len(loop.results) <= 4
        # the newest result still serves complete, digest and all
        code, view = _get(fd.port, "/v1/result/r000011")
        assert code == 200 and view["status"] == "done"
        assert view["result"] == "completed" and "digest" in view
        # a flooded-out rid is the structured 410, not a 404
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{fd.port}/v1/result/r000000", timeout=10
            )
        assert e.value.code == 410
        view = json.loads(e.value.read().decode())
        assert view["status"] == "expired"
        assert "IGG_RESULT_KEEP" in view["detail"]
        assert tele.snapshot()["counters"]["frontdoor.results_expired"] >= 1
        # ...and a rid that never existed is still the honest 404
        code, view = _get(fd.port, "/v1/result/r999999")
        assert code == 404
        # the ledger prune announced itself
        snap = tele.snapshot()["counters"]
        assert snap["frontdoor.requests_pruned_total"] >= 8
        assert snap["serving.results_pruned_total"] >= 8
    finally:
        fd.close()


def test_unconsumed_results_survive_the_ttl(monkeypatch):
    """The retention invariant: a result NOBODY has read (no harvest, no
    digest) is never pruned, however old — a retention knob must not
    lose an answer before its first read."""
    monkeypatch.setenv("IGG_RESULT_TTL_S", "0.001")
    loop = _pool(capacity=2)
    fd = FrontDoor(loop, port=0)
    try:
        for _ in range(2):
            code, _, _ = _post(fd.port, "/v1/submit", {
                "tenant": "t", "model": "diffusion3d",
                "params": {"max_steps": 1},
            })
            assert code == 202
        fd.serve_rounds(max_rounds=6)
        assert sorted(loop.results) == [0, 1]
        for m in loop.results:  # age both far past the TTL
            loop._result_ts[m] = time.monotonic() - 99.0
        loop._consumed.discard(0)  # ...but declare member 0 unread
        loop._prune_results()
        assert 0 in loop.results and 1 not in loop.results
    finally:
        fd.close()
