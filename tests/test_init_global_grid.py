"""Tests for init_global_grid / finalize_global_grid / topology.

Ported from `/root/reference/test/test_init_global_grid.jl` (error cases,
implicit global size, neighbor table) plus TPU-specific mesh assertions.
"""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.parallel import topology


def test_basic_init_returns():
    me, dims, nprocs, coords, mesh = igg.init_global_grid(4, 4, 4, quiet=True)
    assert nprocs == 8
    assert int(np.prod(dims)) == 8
    assert me == 0
    assert coords == topology.coords_of_rank(0, dims)
    assert mesh.axis_names == ("x", "y", "z")
    assert tuple(mesh.devices.shape) == tuple(dims)
    gg = igg.get_global_grid()
    assert gg.nxyz == (4, 4, 4)
    # nxyz_g = dims*(nxyz-overlaps) + overlaps*(periods==0)  (init_global_grid.jl:93)
    assert gg.nxyz_g == tuple(d * (4 - 2) + 2 for d in dims)


def test_double_init_error():
    igg.init_global_grid(4, 4, 4, quiet=True)
    with pytest.raises(RuntimeError, match="already been initialized"):
        igg.init_global_grid(4, 4, 4, quiet=True)


def test_not_initialized_error():
    with pytest.raises(RuntimeError, match="before init_global_grid"):
        igg.nx_g()
    with pytest.raises(RuntimeError, match="before init_global_grid"):
        igg.finalize_global_grid()


def test_invalid_args():
    # /root/reference/test/test_init_global_grid.jl:92-110 error matrix
    with pytest.raises(ValueError, match="nx can never be 1"):
        igg.init_global_grid(1, 4, 4, quiet=True)
    with pytest.raises(ValueError, match="ny cannot be 1 if nz"):
        igg.init_global_grid(4, 1, 4, quiet=True)
    with pytest.raises(ValueError, match="must not be set"):
        igg.init_global_grid(4, 1, 1, dimy=2, quiet=True)
    with pytest.raises(ValueError, match="period"):
        igg.init_global_grid(4, 2, 1, periody=1, dimy=1, quiet=True)  # ny < 2*ol-1
    with pytest.raises(ValueError, match="device_type"):
        igg.init_global_grid(4, 4, 4, device_type="rocm", quiet=True)
    assert not igg.grid_is_initialized()


def test_periodic_global_size():
    me, dims, *_ = igg.init_global_grid(5, 5, 5, periodx=1, periody=1, periodz=1, quiet=True)
    # periodic: no +overlap correction
    assert igg.nx_g() == dims[0] * 3
    assert igg.ny_g() == dims[1] * 3
    assert igg.nz_g() == dims[2] * 3


def test_fixed_dims_and_overlap():
    me, dims, nprocs, coords, mesh = igg.init_global_grid(
        8, 8, 8, dimx=4, dimy=2, dimz=1, overlapx=3, quiet=True
    )
    assert dims == (4, 2, 1)
    assert igg.nx_g() == 4 * (8 - 3) + 3
    assert igg.ny_g() == 2 * (8 - 2) + 2
    assert igg.nz_g() == 1 * (8 - 2) + 2


def test_dims_create():
    assert topology.dims_create(8, (0, 0, 0)) == (2, 2, 2)
    assert topology.dims_create(12, (0, 0, 0)) == (3, 2, 2)
    assert topology.dims_create(6, (0, 3, 0)) == (2, 3, 1)
    assert topology.dims_create(8, (8, 0, 0)) == (8, 1, 1)
    assert topology.dims_create(7, (0, 0, 0)) == (7, 1, 1)
    assert topology.dims_create(16, (0, 0, 0)) == (4, 2, 2)
    with pytest.raises(ValueError):
        topology.dims_create(8, (3, 0, 0))


def test_neighbors_table():
    dims, periods = (2, 2, 2), (0, 0, 1)
    nb = topology.neighbors_table((0, 0, 0), dims, periods)
    # rank of (cx,cy,cz) = (cx*2+cy)*2+cz
    assert nb[0, 0] == igg.PROC_NULL and nb[1, 0] == 4  # x: no lower, upper=(1,0,0)
    assert nb[0, 1] == igg.PROC_NULL and nb[1, 1] == 2  # y
    assert nb[0, 2] == 1 and nb[1, 2] == 1  # z periodic with dims 2: both sides = (0,0,1)
    nb = topology.neighbors_table((1, 1, 1), dims, periods)
    assert nb[1, 0] == igg.PROC_NULL and nb[0, 0] == 3
    # self-neighbor when dims==1 and periodic
    nb = topology.neighbors_table((0, 0, 0), (1, 1, 1), (1, 0, 0))
    assert nb[0, 0] == 0 and nb[1, 0] == 0
    assert nb[0, 1] == igg.PROC_NULL


def test_rank_coords_roundtrip():
    dims = (2, 2, 2)
    for r in range(8):
        assert topology.rank_of_coords(topology.coords_of_rank(r, dims), dims) == r


def test_1d_and_2d_grids():
    me, dims, nprocs, *_ = igg.init_global_grid(4, 1, 1, quiet=True)
    assert dims == (8, 1, 1)
    assert igg.nx_g() == 8 * 2 + 2 and igg.ny_g() == 1 and igg.nz_g() == 1
    igg.finalize_global_grid()
    me, dims, *_ = igg.init_global_grid(4, 4, 1, quiet=True)
    assert dims[2] == 1 and int(np.prod(dims)) == 8


def test_select_device():
    igg.init_global_grid(4, 4, 4, quiet=True)
    dev = igg.select_device()
    assert dev.platform == "cpu"


def test_finalize_then_reinit():
    igg.init_global_grid(4, 4, 4, quiet=True)
    igg.finalize_global_grid()
    assert not igg.grid_is_initialized()
    igg.init_global_grid(5, 5, 5, quiet=True)
    assert igg.get_global_grid().nxyz == (5, 5, 5)


def test_tic_toc():
    igg.init_global_grid(4, 4, 4, quiet=True)
    igg.tic()
    assert igg.toc() >= 0.0
