"""Differentiability through the SPMD machinery — a TPU-first capability.

The reference's halo exchange is imperative MPI with mutable buffers
(`/root/reference/src/update_halo.jl`) and cannot be differentiated; here
`update_halo` is a pure function of its inputs (`lax.ppermute` has a
transpose rule, the PROC_NULL masking is a `where`), so `jax.grad` flows
through the full multi-device step — adjoint/sensitivity solvers and
ML-hybrid pipelines get the exchange's VJP for free.

Oracle: central finite differences in float64 on the 8-device CPU mesh.
The loss is O(1e7) (Gaussian ICs squared over all cells), so the FD quotient
itself carries absolute error ~|loss|*2^-52/eps ≈ 1e-4 — the tolerances are
the FD's honest resolution, not the (exact) analytic gradient's.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import acoustic3d, diffusion3d


def _fd_check(loss, args, wrt, points, eps=1e-5, rtol=1e-3, atol=1e-3):
    g = jax.block_until_ready(jax.grad(loss, argnums=wrt)(*args))
    A = args[wrt]
    for idx in points:
        ap = [*args]
        ap[wrt] = A.at[idx].add(eps)
        am = [*args]
        am[wrt] = A.at[idx].add(-eps)
        fd = (loss(*ap) - loss(*am)) / (2 * eps)
        np.testing.assert_allclose(
            float(g[idx]), float(fd), rtol=rtol, atol=atol, err_msg=str(idx)
        )


def test_grad_through_diffusion_step():
    """grad through stencil + ppermute exchange, checked by FD at interior,
    block-edge, and halo-plane points of the global-block array."""
    state, params = diffusion3d.setup(8, 8, 8, quiet=True, dtype=jnp.float64)
    T, Cp = state
    step = diffusion3d.make_step(params, donate=False)

    def loss(T, Cp):
        T2, _ = step(T, Cp)
        return jnp.sum(T2**2)

    _fd_check(loss, (T, Cp), 0, [(5, 5, 5), (0, 3, 3), (8, 8, 8), (15, 2, 2)])
    # Sensitivity to the coefficient field flows through too.
    _fd_check(loss, (T, Cp), 1, [(5, 5, 5), (9, 9, 9)])
    igg.finalize_global_grid()


def test_grad_through_update_halo_periodic():
    """The self-neighbor (periodic) local-copy path is linear; its VJP must
    route cotangents from the halo planes back to the interior source planes.

    Differentiation happens through a `stencil`-wrapped function (the
    production pattern): calling `update_halo` directly on global arrays
    under `jax.grad` is unsupported — the grad tracer makes it take the
    inline (inside-shard_map) path with no mesh context, and `ppermute`
    has no eval rule outside one."""
    state, params = diffusion3d.setup(
        8, 8, 8, periodx=1, quiet=True, dtype=jnp.float64
    )
    T, _ = state
    exchange = igg.stencil(lambda T: igg.update_halo(T))

    def loss(T):
        return jnp.sum(exchange(T) ** 2)

    _fd_check(loss, (T,), 0, [(1, 4, 4), (14, 4, 4), (7, 7, 7)])
    igg.finalize_global_grid()


def test_grad_through_staggered_multi_step():
    """grad of the acoustic leapfrog chunk (fori_loop of V+P updates with a
    3-field exchange per step) w.r.t. the initial pressure."""
    state, params = acoustic3d.setup(8, 8, 8, quiet=True, dtype=jnp.float64)
    P, Vx, Vy, Vz = state
    multi = acoustic3d.make_multi_step(params, 3, donate=False)

    def loss(P):
        out = multi(P, Vx, Vy, Vz)
        return jnp.sum(out[0] ** 2)

    _fd_check(loss, (P,), 0, [(4, 4, 4), (8, 8, 8), (0, 5, 5)])
    igg.finalize_global_grid()


def test_grad_through_fused_diffusion_multi_step():
    """jax.grad through `make_multi_step(fused_k=...)` (VERDICT r3 #8): the
    Pallas chunk has no VJP, so `fused_with_xla_grad` runs the kernel in the
    primal and differentiates the XLA-cadence twin in the backward pass —
    the gradient must match the XLA cadence's gradient to float rounding."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret
    from implicitglobalgrid_tpu.ops.pallas_stencil import fused_support_error

    nloc = (16, 32, 128)
    # f32: the kernel envelope rejects f64, which would silently test the
    # fallback path instead of the custom-VJP wrapper.
    assert fused_support_error(nloc, 2, 4, 8, 16, zpatch=True) is None
    kw = dict(
        devices=jax.devices()[:1], periodz=1, overlapz=4, quiet=True,
        dtype=jnp.float32,
    )
    state, params = diffusion3d.setup(*nloc, **kw)
    T, Cp = state

    with pallas_force_interpret():
        fused = diffusion3d.make_multi_step(
            params, 2, donate=False, fused_k=2, fused_tile=(8, 16)
        )

        def loss_fused(T, Cp):
            T2, _ = fused(T, Cp)
            return jnp.sum(T2**2) * 1e-6

        g_fused = jax.block_until_ready(jax.grad(loss_fused, argnums=(0, 1))(T, Cp))

    cadence = diffusion3d.make_multi_step(params, 2, donate=False, exchange_every=2)

    def loss_cad(T, Cp):
        T2, _ = cadence(T, Cp)
        return jnp.sum(T2**2) * 1e-6

    g_cad = jax.block_until_ready(jax.grad(loss_cad, argnums=(0, 1))(T, Cp))
    igg.finalize_global_grid()
    for name, gf, gc in zip(("dT", "dCp"), g_fused, g_cad):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gc), rtol=1e-4, atol=1e-4, err_msg=name
        )


def test_grad_through_fused_staggered_multi_step():
    """Same custom-VJP story for a staggered fused chunk (acoustic)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret
    from implicitglobalgrid_tpu.ops.pallas_leapfrog import fused_support_error

    nloc = (16, 32, 128)
    assert fused_support_error(nloc, 2, 4, 8, 16, zpatch=True) is None
    kw = dict(
        devices=jax.devices()[:1], periodz=1, overlapz=4, quiet=True,
        dtype=jnp.float32,
    )
    state, params = acoustic3d.setup(*nloc, **kw)
    P, Vx, Vy, Vz = state

    with pallas_force_interpret():
        fused = acoustic3d.make_multi_step(
            params, 2, donate=False, fused_k=2, fused_tile=(8, 16)
        )

        def loss_fused(P):
            out = fused(P, Vx, Vy, Vz)
            return jnp.sum(out[0] ** 2)

        g_fused = jax.block_until_ready(jax.grad(loss_fused)(P))

    cadence = acoustic3d.make_multi_step(params, 2, donate=False, exchange_every=2)

    def loss_cad(P):
        out = cadence(P, Vx, Vy, Vz)
        return jnp.sum(out[0] ** 2)

    g_cad = jax.block_until_ready(jax.grad(loss_cad)(P))
    igg.finalize_global_grid()
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_cad), rtol=1e-4, atol=1e-4
    )
