"""Batched ensemble execution + serving loop (ISSUE 8).

The two contracts under test:

* **bit-exactness** — a B-stacked batched step is bit-identical, member
  for member, to B independent unbatched runs, across the oracle matrix:
  all three models, coalesce on/off, periodic + PROC_NULL transports in
  one grid (dims (2,2,2), periodz=1), the deep-halo slab cadence and the
  fused Pallas path (the 2-process gloo leg lives in
  ``tests/_distributed_worker.py``);
* **B for the price of 1** — the traced collective count of the batched
  exchange equals the unbatched one per dimension (the full census is
  tier-1 via `analysis.budget`; here the model-level step programs are
  pinned too), and the serving loop's admit/retire/guard machinery acts
  per member.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import (
    _batched,
    acoustic3d,
    diffusion3d,
    porous_convection3d,
)

MODELS = {
    "diffusion": (diffusion3d, 2, {}),
    "acoustic": (acoustic3d, 4, {}),
    "porous": (porous_convection3d, 5, {"npt": 3}),
}


def _members(model, n, B, extra):
    """B single-member states with the batched_setup scales."""
    return [
        model.setup(n, n, n, init_grid=False,
                    ic_scale=1.0 + b / (8.0 * B), **extra)[0]
        for b in range(B)
    ]


def _assert_members_equal(bstate, singles, nf):
    for b, s in enumerate(singles):
        mem = _batched.member_state(bstate, b)
        for i in range(nf):
            np.testing.assert_array_equal(
                np.asarray(mem[i]), np.asarray(s[i]),
                err_msg=f"member {b} field {i} diverged from its "
                        f"independent run",
            )


@pytest.mark.parametrize("name", list(MODELS))
@pytest.mark.parametrize("coalesce", ["1", "0"])
def test_batched_step_matches_independent_runs(name, coalesce, monkeypatch):
    """B-stacked `make_step(batch=True)` ≡ B independent B=1 runs, on a
    grid with BOTH periodic and PROC_NULL transports, coalesce on/off."""
    monkeypatch.setenv("IGG_COALESCE", coalesce)
    model, nf, extra = MODELS[name]
    n, B = 8, 3
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    _, params = model.setup(n, n, n, init_grid=False, **extra)
    singles = _members(model, n, B, extra)
    bstate = _batched.stack_states(singles)

    step1 = model.make_step(params, donate=False)
    stepB = model.make_step(params, donate=False, batch=True)
    for _ in range(2):
        bstate = stepB(*bstate)
        singles = [step1(*s) for s in singles]
    _assert_members_equal(bstate, singles, nf)


def test_batched_slab_cadence_matches_independent(monkeypatch):
    """The deep-halo ``exchange_every`` cadence, batched vs independent —
    the serving loop's production XLA step shape."""
    n, B = 8, 2
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         overlapx=4, overlapy=4, overlapz=4, quiet=True)
    _, params = diffusion3d.setup(n, n, n, init_grid=False)
    singles = _members(diffusion3d, n, B, {})
    bstate = _batched.stack_states(singles)
    step1 = diffusion3d.make_multi_step(params, 4, donate=False,
                                        exchange_every=2)
    stepB = diffusion3d.make_multi_step(params, 4, donate=False,
                                        exchange_every=2, batch=True)
    bstate = stepB(*bstate)
    singles = [step1(*s) for s in singles]
    _assert_members_equal(bstate, singles, 2)


@pytest.mark.parametrize("name", list(MODELS))
def test_batched_fused_cadence_matches_independent(name):
    """The fused Pallas chunks under vmap (interpret mode): the
    pallas_call batching rule must advance every member exactly as its
    own call — all three kernel families (stencil, leapfrog, PT)."""
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    model, nf, extra = MODELS[name]
    if name == "porous":
        extra = {"npt": 4}
    n0, n1, n2, k = 16, 32, 128, 2
    igg.init_global_grid(n0, n1, n2, devices=jax.devices()[:1], quiet=True)
    _, params = model.setup(n0, n1, n2, init_grid=False,
                            dtype=jnp.float32, **extra)
    singles = [
        model.setup(n0, n1, n2, init_grid=False, dtype=jnp.float32,
                    ic_scale=s, **extra)[0]
        for s in (1.0, 1.25)
    ]
    bstate = _batched.stack_states(singles)
    with pallas_force_interpret(), warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        step1 = model.make_multi_step(params, k, donate=False, fused_k=k)
        stepB = model.make_multi_step(params, k, donate=False, fused_k=k,
                                      batch=True)
        bstate = jax.block_until_ready(stepB(*bstate))
        singles = [jax.block_until_ready(step1(*s)) for s in singles]
    _assert_members_equal(bstate, singles, nf)


def test_batched_step_collective_count_is_b_invariant():
    """The traced per-step model program emits the SAME ppermute count
    batched and unbatched (the model-level twin of the budget census)."""
    from implicitglobalgrid_tpu.analysis.budget import _count_ppermutes
    from implicitglobalgrid_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    n = 8
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    state, params = acoustic3d.setup(n, n, n, init_grid=False)
    gg = igg.get_global_grid()

    def count(step, nf, batched, B=4):
        spec = (
            P(None, *igg.AXIS_NAMES) if batched else P(*igg.AXIS_NAMES)
        )
        mapped = shard_map(
            step.__wrapped__, mesh=gg.mesh, in_specs=(spec,) * nf,
            out_specs=(spec,) * nf, check_vma=False,
        )
        args = [
            jax.ShapeDtypeStruct(
                ((B,) + A.shape) if batched else A.shape, A.dtype
            )
            for A in state
        ]
        return _count_ppermutes(jax.make_jaxpr(mapped)(*args).jaxpr)

    c1 = count(acoustic3d.make_step(params, donate=False), 4, False)
    cB = count(acoustic3d.make_step(params, donate=False, batch=True), 4,
               True)
    assert c1 > 0, "census saw no collectives at all"
    assert cB == c1, (
        f"batched step emits {cB} ppermutes vs {c1} unbatched — batching "
        f"must ride the same collectives"
    )


def _rand_field(seed, n=8):
    """A random global-block field (distinct values per block)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    g = np.random.default_rng(seed).normal(size=(2 * n, 2 * n, 2 * n))
    return jax.device_put(g, NamedSharding(gg.mesh, P("x", "y", "z")))


def test_stack_member_set_roundtrip_and_select():
    n = 8
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, quiet=True)
    fields = [_rand_field(i) for i in range(3)]
    B = _batched.stack_fields(*fields)
    assert B.shape == (3,) + fields[0].shape
    for b in range(3):
        np.testing.assert_array_equal(
            np.asarray(_batched.member_field(B, b)), np.asarray(fields[b])
        )
    # set_member writes slot 1 only
    (B2,) = _batched.set_member_state((B + 0,), (fields[0],), 1)
    np.testing.assert_array_equal(np.asarray(B2[1]), np.asarray(fields[0]))
    np.testing.assert_array_equal(np.asarray(B2[0]), np.asarray(fields[0]))
    np.testing.assert_array_equal(np.asarray(B2[2]), np.asarray(fields[2]))
    # select freezes masked members bit-for-bit
    (sel,) = _batched.select_members(
        np.array([True, False, True]), (B + 1.0,), (B + 0,)
    )
    np.testing.assert_array_equal(np.asarray(sel[1]), np.asarray(B[1]))
    np.testing.assert_array_equal(
        np.asarray(sel[0]), np.asarray(B[0]) + 1.0
    )


def test_check_members_finite_flags_only_the_bad_member():
    n = 8
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, quiet=True)
    good = igg.ones((n, n, n), "float64")
    bad = np.ones((2 * n, 2 * n, 2 * n))
    bad[3, 3, 3] = np.inf
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    badf = jax.device_put(bad, NamedSharding(gg.mesh, P("x", "y", "z")))
    B = _batched.stack_fields(good, badf, good)
    flags = _batched.check_members_finite((B,))
    assert flags.tolist() == [False, True, False]


# -- serving loop -------------------------------------------------------------


def _mk_loop(**kw):
    from implicitglobalgrid_tpu.serving import ServingLoop

    _, params = diffusion3d.setup(8, 8, 8, init_grid=False)
    return ServingLoop(diffusion3d, params, **kw), params


def _req(scale, max_steps, tenant="t"):
    from implicitglobalgrid_tpu.serving import Request

    s, _ = diffusion3d.setup(8, 8, 8, init_grid=False, ic_scale=scale)
    return Request(state=s, max_steps=max_steps, tenant=tenant)


def test_serving_mid_flight_admit_and_bit_exact_results():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    loop, params = _mk_loop(capacity=2, steps_per_round=2)
    mids = [loop.submit(_req(1.0 + i * 0.1, 4, tenant=f"t{i}"))
            for i in range(4)]
    res = loop.run(max_rounds=20)
    assert sorted(res) == sorted(mids)
    assert all(r.status == "completed" and r.steps == 4
               for r in res.values())
    # queue (4) > capacity (2): members 2/3 were admitted mid-flight
    assert loop.rounds > 2
    # bit-exact vs a standalone run of member 2
    s, _ = diffusion3d.setup(8, 8, 8, init_grid=False, ic_scale=1.2)
    step = diffusion3d.make_step(params, donate=False)
    for _ in range(4):
        s = step(*s)
    np.testing.assert_array_equal(
        np.asarray(res[mids[2]].state[0]), np.asarray(s[0])
    )


def test_serving_evicts_only_the_nan_member():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    loop, _ = _mk_loop(capacity=2, steps_per_round=1)
    good = _req(1.1, 2)
    bad = _req(1.0, 5)
    T = np.asarray(bad.state[0]).copy()
    T[2, 2, 2] = np.nan
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    bad.state = (
        jax.device_put(T, NamedSharding(gg.mesh, P("x", "y", "z"))),
        bad.state[1],
    )
    m_bad = loop.submit(bad)
    m_good = loop.submit(good)
    res = loop.run(max_rounds=10)
    assert res[m_bad].status == "evicted" and res[m_bad].state is None
    assert res[m_good].status == "completed"
    assert np.isfinite(np.asarray(res[m_good].state[0])).all()


def test_serving_rollback_restores_member_then_gives_up():
    from implicitglobalgrid_tpu.serving import Request

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    loop, _ = _mk_loop(capacity=1, steps_per_round=1,
                       guard_policy="rollback", max_rollbacks=2)
    m = loop.submit(_req(1.0, 3))
    loop.run_round()
    assert loop.slots[0].steps == 1
    # poison the live slot: rollback must rewind to the last good snapshot
    T = np.asarray(_batched.member_field(loop._state[0], 0)).copy()
    T[1, 1, 1] = np.nan
    from jax.sharding import NamedSharding, PartitionSpec as P

    gg = igg.get_global_grid()
    badf = jax.device_put(T, NamedSharding(gg.mesh, P("x", "y", "z")))
    loop._state = _batched.set_member_state(
        loop._state, (badf, _batched.member_field(loop._state[1], 0)), 0
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        loop._guard(loop._mask())
    assert loop.slots[0].rollbacks == 1
    assert not _batched.check_members_finite(loop._state).any()
    res = loop.run(max_rounds=10)
    assert res[m].status == "completed"


def test_serving_porous_convergence_mask():
    from implicitglobalgrid_tpu.serving import Request, ServingLoop

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    _, params = porous_convection3d.setup(8, 8, 8, init_grid=False, npt=3)
    loop = ServingLoop(porous_convection3d, params, capacity=2,
                       steps_per_round=1)

    def member(scale):
        return porous_convection3d.setup(
            8, 8, 8, init_grid=False, npt=3, ic_scale=scale
        )[0]

    m_c = loop.submit(Request(state=member(1.0), max_steps=50, tol=1.0))
    m_b = loop.submit(Request(state=member(0.6), max_steps=2))
    res = loop.run(max_rounds=60)
    assert res[m_c].status == "converged"
    assert res[m_c].residual is not None and res[m_c].residual < 1.0
    assert res[m_b].status == "completed" and res[m_b].steps == 2


def test_serving_rejects_mismatched_state_at_submit():
    """A malformed request must be rejected AT SUBMIT, never queued or
    half-admitted: wrong field count, wrong dtype, wrong shape."""
    from implicitglobalgrid_tpu.serving import Request

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    loop, _ = _mk_loop(capacity=1)
    good = _req(1.0, 2)
    loop.submit(good)  # defines the pool signature; occupies the one slot
    with pytest.raises(ValueError, match="field"):
        loop.submit(Request(state=good.state[:1], max_steps=2))
    wrong_dtype = tuple(A.astype("float32") for A in good.state)
    with pytest.raises(ValueError, match="signature"):
        loop.submit(Request(state=wrong_dtype, max_steps=2))
    # queue-bound requests are validated too (the slot is full)
    with pytest.raises(ValueError, match="field"):
        loop.submit(Request(state=(), max_steps=2))
    res = loop.run(max_rounds=5)  # the good member is unharmed
    assert len(res) == 1 and next(iter(res.values())).status == "completed"


def test_serving_resume_refuses_live_members(tmp_path):
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    ck = str(tmp_path / "pool")
    loop, _ = _mk_loop(capacity=1, checkpoint_every=1, checkpoint_dir=ck)
    loop.submit(_req(1.0, 3))
    loop.run_round()
    loop2, _ = _mk_loop(capacity=1, checkpoint_every=1, checkpoint_dir=ck)
    r = _req(1.1, 2)
    loop2.submit(r)  # live member: resume must refuse to clobber it
    with pytest.raises(RuntimeError, match="live members"):
        loop2.resume()


def test_serving_tol_on_model_without_residual_raises():
    from implicitglobalgrid_tpu.serving import Request

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    loop, _ = _mk_loop(capacity=1)
    r = _req(1.0, 2)
    r.tol = 0.1
    with pytest.raises(ValueError, match="no PT residual"):
        loop.submit(r)


def test_serving_checkpoint_resume_mid_flight(tmp_path):
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    ck = str(tmp_path / "pool")
    loop, params = _mk_loop(capacity=2, steps_per_round=1,
                            checkpoint_every=1, checkpoint_dir=ck)
    m0 = loop.submit(_req(1.0, 4, tenant="a"))
    m1 = loop.submit(_req(1.2, 4, tenant="b"))
    loop.run_round()
    loop.run_round()
    mid_state = _batched.member_state(loop._state, 0)

    loop2, _ = _mk_loop(capacity=2, steps_per_round=1,
                        checkpoint_every=1, checkpoint_dir=ck)
    loop2.prime(mid_state)
    assert loop2.resume()
    assert loop2.rounds == 2 and loop2.active_members == 2
    assert loop2.slots[0].member == m0 and loop2.slots[0].steps == 2
    res = loop2.run(max_rounds=10)
    # the resumed pool finishes both members with the original budgets
    assert res[m0].status == "completed" and res[m0].steps == 4
    assert res[m1].status == "completed"


# -- batched gather -----------------------------------------------------------


def test_gather_member_slices_one_member():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    fields = [_rand_field(10 + i) for i in range(3)]
    B = _batched.stack_fields(*fields)
    for b in (0, 2):
        got = igg.gather(B, member=b)
        want = igg.gather(fields[b])
        np.testing.assert_array_equal(got, want)
    # batched field without member= is rejected, not misread
    with pytest.raises(ValueError, match="member=k"):
        igg.gather(B)
    with pytest.raises(ValueError, match="member must be in"):
        igg.gather(B, member=7)
