"""Tests for the global index math (nx_g/x_g & co).

Ported from `/root/reference/test/test_tools.jl`, including the simulated
3x3x3-topology testset (`:116-166`) with its exact pinned values — indices
translated from the reference's 1-based to this API's 0-based convention
(``x_g(i) == reference x_g(i+1)``).
"""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


def _sim_grid(nx, ny, nz, dims, periods=(0, 0, 0), **kw):
    """Init a 1x1x1 grid then fake a larger topology (the reference's
    simulated-topology trick, test_tools.jl:125-133, enabled here by
    GlobalGrid.replace instead of in-place array mutation)."""
    igg.init_global_grid(nx, ny, nz, dimx=1, dimy=1, dimz=1, quiet=True,
                         devices=[__import__("jax").devices()[0]], **kw)
    gg = igg.get_global_grid()
    nxyz_g = tuple(
        d * (n - o) + o * (p == 0)
        for n, d, o, p in zip(gg.nxyz, dims, gg.overlaps, gg.periods)
    )
    igg.set_global_grid(gg.replace(dims=tuple(dims), nxyz_g=nxyz_g, nprocs=int(np.prod(dims))))
    return igg.get_global_grid()


def test_nxg_staggered_single():
    # reference test_tools.jl testset 1: nx=5,ny=5,nz=5 single proc
    igg.init_global_grid(5, 5, 5, quiet=True, devices=[__import__("jax").devices()[0]])
    A = np.zeros((5, 5, 5))
    Vx = np.zeros((6, 5, 5))
    Sxz = np.zeros((4, 3, 6))
    assert igg.nx_g() == 5 and igg.ny_g() == 5 and igg.nz_g() == 5
    assert igg.nx_g(A) == 5
    assert igg.nx_g(Vx) == 6 and igg.ny_g(Vx) == 5
    assert igg.nx_g(Sxz) == 4 and igg.ny_g(Sxz) == 3 and igg.nz_g(Sxz) == 6


def test_xg_single_proc():
    # reference doctest (src/tools.jl:66-96): lx=4, nx=3 → dx=2; A(3): [0,2,4]; Vx(4): [-1,1,3,5]
    igg.init_global_grid(3, 3, 3, quiet=True, devices=[__import__("jax").devices()[0]])
    lx = 4
    dx = lx / (igg.nx_g() - 1)
    A = np.zeros((3, 3, 3))
    Vx = np.zeros((4, 3, 3))
    assert [igg.x_g(i, dx, A) for i in range(3)] == [0.0, 2.0, 4.0]
    assert [igg.x_g(i, dx, Vx) for i in range(4)] == [-1.0, 1.0, 3.0, 5.0]
    assert [igg.y_g(i, dx, A) for i in range(3)] == [0.0, 2.0, 4.0]
    assert [igg.z_g(i, dx, A) for i in range(3)] == [0.0, 2.0, 4.0]


def test_xg_simulated_3x3x3():
    # reference test_tools.jl:116-166, exact pinned values (0-based here).
    lx, ly, lz = 20, 20, 16
    nx = ny = nz = 5
    _sim_grid(nx, ny, nz, (3, 3, 3), periodz=1)
    P = np.zeros((nx, ny, nz))
    A = np.zeros((nx + 1, ny - 2, nz + 2))
    assert igg.nx_g() == 3 * 3 + 2 == 11
    assert igg.nz_g() == 3 * 3 == 9  # periodic: no overlap correction
    dx = lx / (igg.nx_g() - 1)
    dy = ly / (igg.ny_g() - 1)
    dz = lz / (igg.nz_g() - 1)

    def xs(f, n, d, arr, c):
        return [f(i, d, arr, coords=c) for i in range(n)]

    assert xs(igg.x_g, 5, dx, P, (0, 0, 0)) == [0.0, 2.0, 4.0, 6.0, 8.0]
    assert xs(igg.x_g, 5, dx, P, (1, 0, 0)) == [6.0, 8.0, 10.0, 12.0, 14.0]
    assert xs(igg.x_g, 5, dx, P, (2, 0, 0)) == [12.0, 14.0, 16.0, 18.0, 20.0]
    assert xs(igg.y_g, 5, dy, P, (0, 0, 0)) == [0.0, 2.0, 4.0, 6.0, 8.0]
    assert xs(igg.y_g, 5, dy, P, (0, 1, 0)) == [6.0, 8.0, 10.0, 12.0, 14.0]
    assert xs(igg.y_g, 5, dy, P, (0, 2, 0)) == [12.0, 14.0, 16.0, 18.0, 20.0]
    assert xs(igg.z_g, 5, dz, P, (0, 0, 0)) == [16.0, 0.0, 2.0, 4.0, 6.0]
    assert xs(igg.z_g, 5, dz, P, (0, 0, 1)) == [4.0, 6.0, 8.0, 10.0, 12.0]
    assert xs(igg.z_g, 5, dz, P, (0, 0, 2)) == [10.0, 12.0, 14.0, 16.0, 0.0]
    assert xs(igg.x_g, 6, dx, A, (0, 0, 0)) == [-1.0, 1.0, 3.0, 5.0, 7.0, 9.0]
    assert xs(igg.x_g, 6, dx, A, (1, 0, 0)) == [5.0, 7.0, 9.0, 11.0, 13.0, 15.0]
    assert xs(igg.x_g, 6, dx, A, (2, 0, 0)) == [11.0, 13.0, 15.0, 17.0, 19.0, 21.0]
    assert xs(igg.y_g, 3, dy, A, (0, 0, 0)) == [2.0, 4.0, 6.0]
    assert xs(igg.y_g, 3, dy, A, (0, 1, 0)) == [8.0, 10.0, 12.0]
    assert xs(igg.y_g, 3, dy, A, (0, 2, 0)) == [14.0, 16.0, 18.0]
    assert xs(igg.z_g, 7, dz, A, (0, 0, 0)) == [14.0, 16.0, 0.0, 2.0, 4.0, 6.0, 8.0]
    assert xs(igg.z_g, 7, dz, A, (0, 0, 1)) == [2.0, 4.0, 6.0, 8.0, 10.0, 12.0, 14.0]
    assert xs(igg.z_g, 7, dz, A, (0, 0, 2)) == [8.0, 10.0, 12.0, 14.0, 16.0, 0.0, 2.0]


def test_zg_periodic_seam_no_double_wrap():
    """f64 seam regression (round-3 diffusion z-patch failure's root cause):
    the upper periodic wrap's float cancellation residue (e.g. 125*d - d -
    124*d ~ -2e-15) must not trigger the lower wrap — that landed the seam
    plane a full period outside the domain and broke the wrap invariant
    (plane i == plane i+(n-o)) the halo exchange is built on."""
    import jax

    igg.init_global_grid(
        16, 32, 128, periodz=1, overlapz=4, quiet=True, devices=[jax.devices()[0]]
    )
    lz = 10.0
    dz = lz / (igg.nz_g() - 1)  # 10/123: non-terminating binary, residue case
    A = np.zeros((16, 32, 128))
    z = np.asarray([igg.z_g(i, dz, A) for i in range(128)])
    o = 4
    np.testing.assert_allclose(z[:o], z[128 - o :], rtol=0, atol=1e-12)
    assert (z >= -1e-12).all() and (z <= lz + 1e-12).all()


def test_xg_vectorized():
    igg.init_global_grid(5, 5, 5, quiet=True, devices=[__import__("jax").devices()[0]])
    A = np.zeros((5, 5, 5))
    vec = igg.x_g(np.arange(5), 2.0, A)
    assert np.array_equal(vec, [0.0, 2.0, 4.0, 6.0, 8.0])


def test_coord_fields_match_xg():
    me, dims, *_ = igg.init_global_grid(4, 4, 4, periodz=1, quiet=True)
    dx = dy = dz = 1.5
    T = igg.zeros((4, 4, 4), "float64")
    XG, YG, ZG = igg.coord_fields(T, (dx, dy, dz))
    xg = np.asarray(XG)
    yg = np.asarray(YG)
    zg = np.asarray(ZG)
    D = dims
    for cx in range(D[0]):
        for cy in range(D[1]):
            for cz in range(D[2]):
                blk = np.s_[cx * 4:(cx + 1) * 4, cy * 4:(cy + 1) * 4, cz * 4:(cz + 1) * 4]
                ex = np.asarray([igg.x_g(i, dx, T, coords=(cx, cy, cz)) for i in range(4)])
                ey = np.asarray([igg.y_g(i, dy, T, coords=(cx, cy, cz)) for i in range(4)])
                ez = np.asarray([igg.z_g(i, dz, T, coords=(cx, cy, cz)) for i in range(4)])
                np.testing.assert_allclose(xg[blk], ex[:, None, None] * np.ones((4, 4, 4)))
                np.testing.assert_allclose(yg[blk], ey[None, :, None] * np.ones((4, 4, 4)))
                np.testing.assert_allclose(zg[blk], ez[None, None, :] * np.ones((4, 4, 4)), atol=1e-12)


def test_nxg_staggered_multidevice():
    me, dims, *_ = igg.init_global_grid(4, 4, 4, quiet=True)
    Vx = igg.zeros((5, 4, 4))
    assert igg.nx_g(Vx) == igg.nx_g() + 1
    assert igg.ny_g(Vx) == igg.ny_g()


def test_toc_before_tic_raises():
    # PR-4 satellite: toc() with no chronometer started must raise instead
    # of returning nonsense measured from an arbitrary epoch (the old
    # module-load-epoch behavior).  init_global_grid's internal timing
    # priming must NOT count as a user tic().
    igg.init_global_grid(8, 8, 8, quiet=True)
    with pytest.raises(RuntimeError, match=r"toc\(\) called before tic\(\)"):
        igg.toc()
    igg.tic()
    assert igg.toc() >= 0.0
