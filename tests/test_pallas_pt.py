"""Tests for the temporally-blocked fused PT-iteration Pallas kernel.

Same harness as `tests/test_pallas_leapfrog.py` (interpret-mode kernel on
the CPU suite; compiled equivalence + numbers from `bench.py` /
`scripts/verify_tpu.py` on the real chip).

Oracle: ``fused_pt_iterations(..., k)`` vs ``k`` applications of the porous
model's `_flux_update` + `_pressure_update` pair — scale-relative few-ULP
agreement (the kernel multiplies by precomputed ``1/dx`` where the XLA path
divides; flux magnitudes scale as ``|grad Pf|/dx``, so comparisons are
normalized by each field's scale), bit-exact frozen flux boundary faces,
Pf evolving at all cells, and T read-only.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from implicitglobalgrid_tpu.models.porous_convection3d import (
    Params,
    _flux_update,
    _pressure_update,
)
from implicitglobalgrid_tpu.ops.pallas_pt import (
    default_tile,
    fused_pt_iterations,
    fused_support_error,
    pad_faces,
    unpad_faces,
)


def _setup(shape, seed=0, spacing=(0.1, 0.15, 0.2), dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    n0, n1, n2 = shape
    T = jnp.asarray(rng.standard_normal(shape), dtype)
    Pf = jnp.asarray(rng.standard_normal(shape), dtype)
    qDx = jnp.asarray(0.1 * rng.standard_normal((n0 + 1, n1, n2)), dtype)
    qDy = jnp.asarray(0.1 * rng.standard_normal((n0, n1 + 1, n2)), dtype)
    qDz = jnp.asarray(0.1 * rng.standard_normal((n0, n1, n2 + 1)), dtype)
    dx, dy, dz = spacing
    params = Params(
        Ra=100.0, lam_T=0.01, dx=dx, dy=dy, dz=dz,
        theta_q=0.5, beta_p=3e-4, dtype=dtype,
    )
    return (T, Pf, qDx, qDy, qDz), params


def _xla_iters(state, params, k):
    fu = _flux_update(params)
    pu = _pressure_update(params)
    T = state[0]

    @jax.jit
    def it(Pf, qDx, qDy, qDz):
        qDx, qDy, qDz = fu(T, Pf, qDx, qDy, qDz)
        return pu(Pf, qDx, qDy, qDz), qDx, qDy, qDz

    s = state[1:]
    for _ in range(k):
        s = it(*s)
    return s


def _fused_interpret(state, params, k, **kw):
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    T, Pf, qDx, qDy, qDz = state
    qxp, qyp, qzp = pad_faces(qDx, qDy, qDz)
    with pallas_force_interpret():
        Pf, qxp, qyp, qzp = fused_pt_iterations(
            T, Pf, qxp, qyp, qzp, k,
            params.theta_q,
            1.0 / params.dx, 1.0 / params.dy, 1.0 / params.dz,
            params.Ra * params.lam_T, params.beta_p, **kw,
        )
    return (Pf, *unpad_faces(qxp, qyp, qzp))


def _assert_scale_close(got, ref, names, tol=2e-5):
    for name, g, r in zip(names, got, ref):
        g, r = np.asarray(g), np.asarray(r)
        scale = max(float(np.abs(r).max()), 1.0)
        assert float(np.abs(g - r).max()) / scale < tol, name


@pytest.mark.parametrize(
    "k,shape,tile",
    [
        (2, (16, 32, 128), dict(bx=8, by=16)),
        (4, (16, 32, 128), dict(bx=8, by=16)),
        (6, (32, 32, 128), dict(bx=8, by=16)),
        # k=8: in the envelope since round 5 (H=16 y-halo margin)
        (8, (32, 64, 128), dict(bx=8, by=16)),
    ],
)
def test_fused_matches_k_single_iterations(k, shape, tile):
    state, params = _setup(shape)
    ref = _xla_iters(state, params, k)
    got = _fused_interpret(state, params, k, **tile)
    _assert_scale_close(got, ref, ("Pf", "qDx", "qDy", "qDz"))
    # Frozen flux boundary faces: bit-exact.
    for g0, q0 in zip(got[1:], state[2:]):
        g0, q0 = np.asarray(g0), np.asarray(q0)
        for ax in range(3):
            assert np.array_equal(np.take(g0, 0, axis=ax), np.take(q0, 0, axis=ax))
            last = g0.shape[ax] - 1
            assert np.array_equal(
                np.take(g0, last, axis=ax), np.take(q0, last, axis=ax)
            )
    # Pf evolves at the global boundary (all-cells update).
    Pf0, Pfk = np.asarray(state[1]), np.asarray(got[0])
    for ax in range(3):
        assert not np.array_equal(np.take(Pfk, 0, axis=ax), np.take(Pf0, 0, axis=ax))


def test_buoyancy_reaches_z_faces_only():
    # With grad(Pf) = 0 and q = 0, one iteration must produce flux ONLY on
    # interior z-faces (th * Ra*lam_T * av_z(T)) — the x/y fluxes stay zero.
    shape = (16, 32, 128)
    rng = np.random.default_rng(7)
    T = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    Pf = jnp.zeros(shape, jnp.float32)
    z = [
        jnp.zeros((17, 32, 128), jnp.float32),
        jnp.zeros((16, 33, 128), jnp.float32),
        jnp.zeros((16, 32, 129), jnp.float32),
    ]
    state = (T, Pf, *z)
    _, params = _setup(shape)
    got = _fused_interpret(state, params, 2, bx=8, by=16)
    ref = _xla_iters(state, params, 2)
    _assert_scale_close(got, ref, ("Pf", "qDx", "qDy", "qDz"))
    assert float(np.abs(np.asarray(got[3])).max()) > 0.0  # qDz moved
    # qDx/qDy only react through the induced pressure gradient, never at the
    # first iteration; check iteration count 2 left them matching XLA above.


def test_t_input_buffer_unmodified():
    # T has no output alias; the kernel must not write through the input
    # buffer either (a donation/aliasing bug would).  Snapshot the device
    # buffer before and compare after.
    state, params = _setup((16, 32, 128), seed=9)
    t_before = np.asarray(state[0]).copy()
    got = _fused_interpret(state, params, 2, bx=8, by=16)
    assert not np.array_equal(np.asarray(got[0]), np.asarray(state[1]))  # Pf moved
    np.testing.assert_array_equal(np.asarray(state[0]), t_before)


def test_bfloat16_structure():
    # Structural correctness at bf16 accuracy + bit-exact frozen flux faces
    # (same coverage bar as the diffusion and leapfrog kernels).
    state, params = _setup((16, 32, 128), seed=11, dtype=jnp.bfloat16)
    ref = _xla_iters(state, params, 2)
    got = _fused_interpret(state, params, 2, bx=8, by=16)
    for name, g, r in zip(("Pf", "qDx", "qDy", "qDz"), got, ref):
        g = np.asarray(g.astype(jnp.float32))
        r = np.asarray(r.astype(jnp.float32))
        scale = max(float(np.abs(r).max()), 1.0)
        assert float(np.abs(g - r).max()) / scale < 0.05, name
    q0, qk = np.asarray(state[2].astype(jnp.float32)), np.asarray(
        got[1].astype(jnp.float32)
    )
    assert np.array_equal(qk[0], q0[0])
    assert np.array_equal(qk[-1], q0[-1])


def test_envelope_validation():
    state, params = _setup((16, 32, 128))
    T, Pf, qDx, qDy, qDz = state
    qxp, qyp, qzp = pad_faces(qDx, qDy, qDz)
    args = (0.5, 10.0, 10.0, 10.0, 1.0, 1e-3)
    with pytest.raises(ValueError, match="k must be even"):
        fused_pt_iterations(T, Pf, qxp, qyp, qzp, 3, *args)
    with pytest.raises(ValueError, match="pad_faces layout"):
        fused_pt_iterations(T, Pf, qDx, qDy, qDz, 2, *args)
    with pytest.raises(ValueError, match="cell shape"):
        fused_pt_iterations(T[:-1], Pf, qxp, qyp, qzp, 2, *args)
    assert "multiple of 128" in fused_support_error((16, 32, 192), 2)
    assert default_tile((64, 128, 128), 2) == (32, 64)
    # The 14-buffer VMEM accounting prunes earlier than the leapfrog's 12.
    assert "VMEM" in fused_support_error((256, 256, 512), 6, 4, 32, 64)
