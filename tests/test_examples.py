"""Smoke tests for the example scripts (the reference ships 5 runnable
`examples/diffusion3D_*` variants; these are their ports — they must stay
importable and runnable, not just exist)."""

import importlib.util
import os
import sys

import numpy as np
import pytest

_examples = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def _load(name):
    spec = importlib.util.spec_from_file_location(name, os.path.join(_examples, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_multidevice_novis_runs():
    import implicitglobalgrid_tpu as igg

    mod = _load("diffusion3d_multidevice_novis")
    T = mod.diffusion3d(nx=8, nt=3)
    T = np.asarray(T)
    assert T.shape == (16, 16, 16)  # 2x2x2 blocks of 8^3
    assert np.isfinite(T).all()
    assert T.max() > 0  # the Gaussian anomaly diffused, not zeroed
    assert not igg.grid_is_initialized()  # example finalizes after itself


def test_multidevice_vis_runs(tmp_path):
    import implicitglobalgrid_tpu as igg

    mod = _load("diffusion3d_multidevice")
    mod.diffusion3d_vis(nx=8, nt=4, nvis=2, outdir=str(tmp_path))
    # frames (npy fallback) or a gif must have been produced on process 0
    produced = list(tmp_path.iterdir())
    assert produced, "visualization example produced no output"
    assert not igg.grid_is_initialized()


def test_tpu_onlyvis_importable():
    # The single-device variants guard real work behind __main__/functions;
    # importing them must not initialize a grid or crash.
    import implicitglobalgrid_tpu as igg

    for name in ("diffusion3d_tpu", "diffusion3d_tpu_novis", "diffusion3d_tpu_onlyvis"):
        _load(name)
    assert not igg.grid_is_initialized()


def test_tpu_onlyvis_recipe_runs():
    # The onlyvis visualization recipe (strip halo -> gather -> mid-plane
    # frame) must execute end to end on a tiny grid, like the reference's
    # examples/diffusion3D_multigpu_CuArrays_onlyvis.jl recipe.
    import implicitglobalgrid_tpu as igg

    mod = _load("diffusion3d_tpu_onlyvis")
    frames = mod.diffusion3d(nx=8, nt=4, nvis=2, quiet=True)
    assert len(frames) == 2  # it = 0 and 2
    gg_dims = 2  # 8 devices -> 2x2x2
    for f in frames:
        assert f.shape == ((8 - 2) * gg_dims, (8 - 2) * gg_dims)
        assert np.isfinite(f).all()
    assert not igg.grid_is_initialized()


def test_tpu_fused_runs():
    # The deep-halo temporal-blocking example on the virtual mesh (interpret-
    # mode kernel; overlap=2k licenses fused_k=k on the communicating grid).
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    import implicitglobalgrid_tpu as igg

    import jax

    mod = _load("diffusion3d_tpu_fused")
    with pallas_force_interpret():
        T = mod.diffusion3d_fused(
            nx=32, nt=4, k=2, quiet=True,
            devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
        )
    T = np.asarray(T)
    gshape = T.shape
    assert np.isfinite(T).all() and T.max() > 0
    assert not igg.grid_is_initialized()


def test_tpu_zsplit_fused_runs():
    # The round-4 z-split production example: 2 devices are forced onto
    # dimz=2, so the in-kernel z-slab apply + export cadence is the
    # exercised path (interpret-mode kernel).
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    import jax

    import implicitglobalgrid_tpu as igg

    from implicitglobalgrid_tpu.ops.pallas_stencil import fused_support_error

    # Local blocks (16, 32, 128): inside the kernel envelope, so the example
    # runs the real z-patch cadence, not the warn-once XLA fallback.
    assert fused_support_error((16, 32, 128), 2, 4, zpatch=True) is None
    mod = _load("diffusion3d_tpu_zsplit_fused")
    with pallas_force_interpret():
        T = mod.diffusion3d_zsplit(
            nx=16, ny=32, nz=128, nt=4, k=2, quiet=True,
            devices=jax.devices()[:2],
        )
    T = np.asarray(T)
    assert np.isfinite(T).all() and T.max() > 0
    assert not igg.grid_is_initialized()


def test_acoustic_fused_runs():
    # The staggered fused example on the virtual mesh (interpret-mode
    # kernel; per-block (16, 32, 128) fits the (8, 16) tile envelope at
    # k=2 — the nx=256 k=6 production default is a hardware config).
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg

    mod = _load("acoustic3d_tpu_fused")
    with pallas_force_interpret():
        P = mod.acoustic3d_fused(
            nx=16, ny=32, nz=128, nt=4, k=2, fused_tile=(8, 16), quiet=True,
            devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
        )
    assert np.isfinite(np.asarray(P)).all()
    assert not igg.grid_is_initialized()


def test_porous_fused_runs():
    # The flagship's fused production example on the virtual mesh
    # (interpret-mode kernel; per-block (16, 32, 128) fits (8, 16) at w=2).
    from implicitglobalgrid_tpu.utils.compat import pallas_force_interpret

    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg

    mod = _load("porous_convection3d_tpu_fused")
    with pallas_force_interpret():
        T = mod.porous_convection3d_fused(
            nx=16, ny=32, nz=128, nt=2, w=2, npt=4, fused_tile=(8, 16),
            quiet=True, devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
        )
    assert np.isfinite(np.asarray(T)).all()
    assert not igg.grid_is_initialized()
